"""DocumentMapper: JSON source -> typed per-field values ready for the
segment writer.

Analog of DocumentMapper/DocumentParser (index/mapper/DocumentMapper.java:247,
DocumentParser.java): walks the JSON tree, resolves dotted paths against the
mapping, applies dynamic mapping for unseen fields, supports multi-fields
(``fields.keyword`` sub-fields) and arrays (multi-valued fields).

Output is a ``ParsedDocument`` holding, per field:
- ``tokens``:  [(term, position)] destined for the inverted index
- ``longs`` / ``doubles`` / ``ordinals``: multi-valued doc-value lists
  (the SortedNumericDocValues / SortedSetDocValues analog — every value
  lands in the column, matching Lucene array-field semantics)
- ``vectors``: dense float vectors (single-valued, like Lucene KnnVectorField)
- ``geo_points``: (lat, lon) pairs

Metadata slots (``_seq_no`` / ``_version`` analog, assigned by the engine):
``seq_no`` and ``version`` fields on ParsedDocument.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field as dc_field
from typing import Any, Optional

from opensearch_tpu.analysis import AnalysisRegistry
from opensearch_tpu.common.errors import (IllegalArgumentError, MapperParsingError, StrictDynamicMappingError)
from opensearch_tpu.mapping.types import (
    FieldType,
    TextFieldType,
    build_field_type,
)

POSITION_GAP = 100  # position increment between array elements (Lucene default)

# Mapping keys that are configuration, not field definitions
# (index/mapper/RootObjectMapper + metadata mappers).
_MAPPING_META_KEYS = frozenset(
    {"dynamic", "_source", "_routing", "_meta", "date_detection",
     "numeric_detection", "dynamic_templates", "_id", "enabled"}
)


@dataclass
class ParsedDocument:
    doc_id: str
    source: dict
    routing: Optional[str] = None
    seq_no: int = -1  # _seq_no metadata slot, assigned by the engine
    version: int = 1  # _version metadata slot, assigned by the engine
    tokens: dict[str, list[tuple[str, int]]] = dc_field(default_factory=dict)
    longs: dict[str, list[int]] = dc_field(default_factory=dict)
    doubles: dict[str, list[float]] = dc_field(default_factory=dict)
    ordinals: dict[str, list[str]] = dc_field(default_factory=dict)
    vectors: dict[str, list[float]] = dc_field(default_factory=dict)
    geo_points: dict[str, list[tuple[float, float]]] = dc_field(default_factory=dict)
    field_lengths: dict[str, int] = dc_field(default_factory=dict)  # for BM25 norms
    # completion field -> [(input, weight)] — weights are PER INPUT
    completions: dict[str, list[tuple[str, int]]] = dc_field(
        default_factory=dict)
    # nested path -> [per-object {child_path: ("num"|"ord", [values])}]
    nested: dict[str, list[dict]] = dc_field(default_factory=dict)


def _dynamic_type_for(value: Any) -> Optional[dict]:
    """Dynamic mapping inference (DocumentParser dynamic templates default)."""
    if isinstance(value, bool):
        return {"type": "boolean"}
    if isinstance(value, int):
        return {"type": "long"}
    if isinstance(value, float):
        return {"type": "float"}
    if isinstance(value, str):
        # Reference default: text with a .keyword sub-field (ignore_above 256).
        return {"type": "text", "fields": {"keyword": {"type": "keyword", "ignore_above": 256}}}
    return None


class DocumentMapper:
    """Holds the field-type lookup for one index and parses documents.

    Thread-safe for concurrent parse + dynamic mapping update (the engine may
    index from several threads, like the reference's write threadpool).
    """

    def __init__(self, mapping: Optional[dict] = None, analysis_settings: Optional[dict] = None):
        self._lock = threading.RLock()
        self.analyzers = AnalysisRegistry(analysis_settings)
        self._fields: dict[str, FieldType] = {}
        self._field_configs: dict[str, dict] = {}
        self.dynamic = "true"  # "true" | "false" | "strict"
        # _source meta-field: enabled=false stops storing source bytes
        # (SourceFieldMapper.enabled) — GET/_source then 404s and hits
        # carry no _source
        self.source_enabled = True
        if mapping:
            self.merge(mapping)

    # --- mapping management ---------------------------------------------

    def merge(self, mapping: dict):
        """Merge a mapping update (PutMappingRequest analog).  Conflicting
        type changes are rejected like MapperService.merge does."""
        with self._lock:
            # Validate everything before mutating any state: a rejected merge
            # must leave the mapper unchanged (MapperService.merge is atomic).
            dynamic = mapping.get("dynamic", self.dynamic)
            if isinstance(dynamic, bool):
                new_dynamic = "true" if dynamic else "false"
            else:
                new_dynamic = str(dynamic).lower()
                if new_dynamic not in ("true", "false", "strict"):
                    raise MapperParsingError(
                        f"dynamic must be one of [true, false, strict], got [{dynamic}]"
                    )
            if "properties" in mapping:
                props = mapping["properties"]
                unknown = [
                    k for k in mapping
                    if k != "properties" and k not in _MAPPING_META_KEYS
                ]
                if unknown:
                    raise MapperParsingError(
                        f"unsupported mapping parameters {sorted(unknown)}"
                    )
            else:
                # Bare field dict shorthand — only valid if every remaining
                # value is itself a field config object.
                props = {k: v for k, v in mapping.items() if k not in _MAPPING_META_KEYS}
                if not all(isinstance(v, dict) for v in props.values()):
                    raise MapperParsingError(
                        "malformed mapping: expected [properties] to be an object of field definitions"
                    )
            if not isinstance(props, dict):
                raise MapperParsingError("malformed mapping: [properties] must be an object")
            # Copy-on-write: build the merged lookup aside and swap it in
            # atomically, so concurrent parse() (which reads _fields without
            # the lock) sees either the old or the new mapping, never a
            # partially-applied one (MapperService.merge is atomic).
            new_fields = dict(self._fields)
            new_configs = dict(self._field_configs)
            self._merge_props("", props, new_fields, new_configs)
            self._fields = new_fields
            self._field_configs = new_configs
            self.dynamic = new_dynamic
            src_meta = mapping.get("_source")
            if isinstance(src_meta, dict) and "enabled" in src_meta:
                self.source_enabled = bool(src_meta["enabled"])

    def _merge_props(self, prefix: str, props: dict,
                     fields: dict, configs: dict):
        for name, config in props.items():
            if not str(name):
                raise IllegalArgumentError(
                    "field name cannot be an empty string")
            path = f"{prefix}{name}"
            if "properties" in config and config.get(
                    "type", "object") == "object":
                # implicit or explicit object container: children map
                # flattened under the dotted path (ObjectMapper)
                self._merge_props(path + ".", config["properties"], fields, configs)
                continue
            if config.get("type") == "nested":
                # the nested container registers AND its children do,
                # under the full dotted path (object-major columns)
                existing = fields.get(path)
                ft = build_field_type(path, config)
                if existing is not None and \
                        existing.type_name != ft.type_name:
                    raise MapperParsingError(
                        f"mapper [{path}] cannot be changed from type "
                        f"[{existing.type_name}] to [nested]")
                fields[path] = ft
                configs[path] = {k: v for k, v in config.items()
                                 if k != "properties"}
                self._merge_props(path + ".",
                                  config.get("properties") or {},
                                  fields, configs)
                continue
            existing = fields.get(path)
            ft = build_field_type(path, config)
            if existing is not None and existing.type_name != ft.type_name:
                raise MapperParsingError(
                    f"mapper [{path}] cannot be changed from type [{existing.type_name}]"
                    f" to [{ft.type_name}]"
                )
            fields[path] = ft
            configs[path] = config
            for sub_name, sub_config in (config.get("fields") or {}).items():
                sub_path = f"{path}.{sub_name}"
                fields[sub_path] = build_field_type(sub_path, sub_config)

    def field_type(self, path: str) -> Optional[FieldType]:
        return self._fields.get(path)

    def field_types(self) -> dict[str, FieldType]:
        with self._lock:
            return dict(self._fields)

    def to_mapping(self) -> dict:
        """Render the current mapping back to JSON (GetMappings analog)."""
        with self._lock:
            root: dict = {}
            for path, config in sorted(self._field_configs.items()):
                parts = path.split(".")
                node = root
                for p in parts[:-1]:
                    node = node.setdefault(p, {}).setdefault("properties", {})
                node[parts[-1]] = dict(config)
            out = {"properties": root}
            if self.dynamic != "true":
                out["dynamic"] = self.dynamic
            return out

    # --- parsing ---------------------------------------------------------

    def parse(self, doc_id: str, source: dict, routing: Optional[str] = None) -> ParsedDocument:
        doc = ParsedDocument(doc_id=doc_id, source=source, routing=routing)
        self._parse_object("", source, doc)
        return doc

    def _parse_object(self, prefix: str, obj: dict, doc: ParsedDocument):
        from opensearch_tpu.mapping.types import NestedFieldType

        for key, value in obj.items():
            path = f"{prefix}{key}"
            ft0 = self._fields.get(path)
            if isinstance(ft0, NestedFieldType):
                self._parse_nested(path, value, doc)
                continue
            if isinstance(value, dict) and ft0 is None:
                self._parse_object(path + ".", value, doc)
                continue
            values = value if isinstance(value, list) else [value]
            # Arrays of objects flatten into the same dotted paths
            # (DocumentParser flattens object arrays; sub-fields accumulate
            # multi-valued data across elements).
            if self._fields.get(path) is None and any(isinstance(v, dict) for v in values):
                for v in values:
                    if isinstance(v, dict):
                        self._parse_object(path + ".", v, doc)
                values = [v for v in values if not isinstance(v, dict)]
                if not values:
                    continue
            ft = self._resolve(path, values)
            if ft is None:
                continue
            # A numeric array IS the single value for vector and geo fields.
            if ft.dv_kind in ("vector", "geo_point") and isinstance(value, list):
                values = [value]
            self._index_values(ft, values, doc)
            # multi-fields share the same raw values
            for sub_path, sub_ft in self._subfields(path):
                self._index_values(sub_ft, values, doc)

    def _parse_nested(self, path: str, value, doc: ParsedDocument):
        """Each element of a nested array becomes ONE object record whose
        child values stay grouped (vs the flattening object-array path
        above — that cross-object mixing is exactly what nested
        prevents).  Child values are stored match-ready: numeric/date/
        boolean as numbers, keyword as terms, text as analyzed terms."""
        if value is None:
            return
        objs = value if isinstance(value, list) else [value]
        records = doc.nested.setdefault(path, [])
        for o in objs:
            if not isinstance(o, dict):
                raise MapperParsingError(
                    f"object mapping for [{path}] tried to parse field "
                    "as object, but found a concrete value")
            record: dict = {}
            self._collect_nested_values(path + ".", o, record)
            records.append(record)

    def _collect_nested_values(self, prefix: str, obj: dict,
                               record: dict):
        for key, v in obj.items():
            child = f"{prefix}{key}"
            if isinstance(v, dict) and self._fields.get(child) is None:
                self._collect_nested_values(child + ".", v, record)
                continue
            ft = self._fields.get(child)
            if ft is None:
                continue           # unmapped nested children are ignored
            values = v if isinstance(v, list) else [v]
            kind, out = None, []
            for item in values:
                if item is None:
                    continue
                if ft.dv_kind in ("long", "double"):
                    dv = ft.doc_value(item)
                    if dv is None:
                        continue
                    kind = "num"
                    out.append(float(dv))
                elif ft.dv_kind == "ordinal":
                    dv = ft.doc_value(item)
                    if dv is None:     # e.g. keyword past ignore_above
                        continue
                    kind = "ord"
                    out.append(str(dv))
                elif hasattr(ft, "search_terms"):      # text: terms only
                    kind = "ord"
                    out.extend(t for t, _p in
                               ft.index_terms(item, self.analyzers))
            if out:
                prev = record.get(child)
                if prev is not None:
                    prev[1].extend(out)
                else:
                    record[child] = (kind, out)

    def _subfields(self, path: str):
        prefix = path + "."
        return [
            (p, ft)
            for p, ft in self._fields.items()
            if p.startswith(prefix)
            and "." not in p[len(prefix):]
            and p not in self._field_configs  # only multi-field children
        ]

    def _resolve(self, path: str, values: list) -> Optional[FieldType]:
        with self._lock:
            ft = self._fields.get(path)
            if ft is not None:
                return ft
            # Strict mode rejects the mere introduction of an unmapped field,
            # even with a null/empty value (DocumentParser strict semantics).
            if self.dynamic == "strict":
                raise StrictDynamicMappingError(path)
            sample = next((v for v in values if v is not None), None)
            if sample is None:
                return None
            if self.dynamic == "false":
                return None
            if isinstance(sample, dict):
                return None  # handled by recursion
            config = _dynamic_type_for(sample)
            if config is None:
                return None
            new_fields = dict(self._fields)
            new_configs = dict(self._field_configs)
            self._merge_props("", _nest(path, config), new_fields, new_configs)
            self._fields = new_fields
            self._field_configs = new_configs
            return self._fields[path]

    def _index_values(self, ft: FieldType, values: list, doc: ParsedDocument):
        if not getattr(ft, "allow_multiple", True) and \
                sum(1 for v in values if v is not None) > 1:
            raise MapperParsingError(
                f"field [{ft.name}] of type [{ft.type_name}] does not "
                "support arrays")
        from opensearch_tpu.mapping.types import (CompletionFieldType,
                                                  JoinFieldType)
        if isinstance(ft, CompletionFieldType):
            # {"input": [...], "weight": n} | "text" | ["a", "b"]:
            # inputs land in the sorted ordinal column (the prefix
            # range), weights stay PER INPUT in a dedicated structure
            # (CompletionFieldMapper.parse keeps weight per entry)
            for v in values:
                if v is None:
                    continue
                if isinstance(v, dict):
                    inputs = v.get("input") or []
                    if isinstance(inputs, str):
                        inputs = [inputs]
                    weight = int(v.get("weight", 1))
                else:
                    inputs, weight = [str(v)], 1
                for text in inputs:
                    doc.ordinals.setdefault(ft.name, []).append(str(text))
                    doc.completions.setdefault(ft.name, []).append(
                        (str(text), weight))
            return
        if isinstance(ft, JoinFieldType):
            # join values land in the hidden #name / #parent ordinal
            # columns (ParentJoinFieldMapper's joinField + parentIdField)
            for v in values:
                if v is None:
                    continue
                if isinstance(v, str):
                    name, parent = v, None
                elif isinstance(v, dict):
                    name, parent = v.get("name"), v.get("parent")
                else:
                    raise MapperParsingError(
                        f"[{ft.name}] join value must be a relation name "
                        "or {name, parent}")
                if not ft.is_relation(name):
                    raise MapperParsingError(
                        f"unknown join name [{name}] for field "
                        f"[{ft.name}]")
                if ft.parent_of(name) is not None and parent is None:
                    raise MapperParsingError(
                        f"[parent] is missing for join field [{ft.name}]")
                doc.ordinals.setdefault(f"{ft.name}#name",
                                        []).append(str(name))
                if parent is not None:
                    doc.ordinals.setdefault(f"{ft.name}#parent",
                                            []).append(str(parent))
            return
        pos_base = 0
        n_tokens = doc.field_lengths.get(ft.name, 0)
        saw_value = any(v is not None for v in values)
        toks = doc.tokens.setdefault(ft.name, [])
        if toks:
            pos_base = toks[-1][1] + POSITION_GAP
        for v in values:
            if v is None:
                continue
            if ft.index_enabled and ft.indexed:
                terms = ft.index_terms(v, self.analyzers)
                for term, pos in terms:
                    toks.append((term, pos_base + pos))
                if terms:
                    pos_base = toks[-1][1] + POSITION_GAP
                if isinstance(ft, TextFieldType):
                    n_tokens += len(terms)
            if ft.doc_values_enabled:
                dv = ft.doc_value(v)
                if dv is None:
                    continue
                kind = ft.dv_kind
                if kind == "long":
                    doc.longs.setdefault(ft.name, []).append(dv)
                elif kind == "double":
                    doc.doubles.setdefault(ft.name, []).append(dv)
                elif kind == "ordinal":
                    doc.ordinals.setdefault(ft.name, []).append(dv)
                elif kind == "vector":
                    if ft.name in doc.vectors:
                        # Lucene KnnVectorField rejects multi-valued vectors
                        raise MapperParsingError(
                            f"[{ft.name}] of type [dense_vector] doesn't "
                            "support indexing multiple values per document"
                        )
                    doc.vectors[ft.name] = dv
                elif kind == "geo_point":
                    doc.geo_points.setdefault(ft.name, []).append(dv)
        if saw_value and ft.index_enabled and not ft.doc_values_enabled \
                and not toks:
            # doc_values disabled and no indexed terms (numeric/date):
            # record a presence marker so `exists` keeps working (the
            # reference indexes points + _field_names for this)
            toks.append(("\x01present", 0))
        if not toks:
            doc.tokens.pop(ft.name, None)
        # field_lengths presence == "this doc has the field" (the norms-entry
        # analog: Lucene writes a norm even for zero-token values, so exists
        # must match them — but a null value writes nothing).
        if isinstance(ft, TextFieldType) and (saw_value or ft.name in doc.field_lengths):
            doc.field_lengths[ft.name] = n_tokens


def _nest(path: str, config: dict) -> dict:
    parts = path.split(".")
    out: dict = {parts[-1]: config}
    for p in reversed(parts[:-1]):
        out = {p: {"properties": out}}
    return out
