from opensearch_tpu.mapping.mapper import DocumentMapper, ParsedDocument  # noqa: F401
from opensearch_tpu.mapping.types import FieldType, build_field_type  # noqa: F401
