"""Field types: how a JSON value becomes index terms + doc-value columns and
how query-time literals are converted for comparison.

Analog of the reference's MappedFieldType hierarchy
(index/mapper/MappedFieldType.java and the ~30 concrete mappers in
index/mapper/).  The TPU twist: every field type declares which *columnar*
representation its doc values take (int64 / float64 / ordinal), because
filters, sorts and aggregations execute as dense vectorized ops over those
columns on device, not via per-doc iterators.

Doc-value column kinds:
- ``long``    -> int64 column (longs, dates as epoch millis, booleans as 0/1, ips)
- ``double``  -> float64 column
- ``ordinal`` -> int32 ordinal column + per-segment sorted term dict (keywords)
- ``none``    -> no column (text fields: inverted index only, like Lucene
                 text fields without fielddata)
"""

from __future__ import annotations

import datetime as _dt
import ipaddress
import math
from typing import Any, Optional

from opensearch_tpu.common.errors import IllegalArgumentError, MapperParsingError


def parse_date_millis(value: Any) -> int:
    """Parse a date literal to epoch millis.

    Supports epoch_millis (int), ISO-8601 date/date-time (the reference's
    default ``strict_date_optional_time||epoch_millis`` format,
    index/mapper/DateFieldMapper.java), and date-only strings.
    """
    if isinstance(value, bool):
        raise MapperParsingError(f"cannot parse date from boolean [{value}]")
    if isinstance(value, (int, float)):
        return int(value)
    s = str(value).strip()
    if s.isdigit() or (s.startswith("-") and s[1:].isdigit()):
        return int(s)
    txt = s.replace("Z", "+00:00")
    try:
        if "T" in txt or " " in txt:
            dt = _dt.datetime.fromisoformat(txt)
        else:
            dt = _dt.datetime.fromisoformat(txt + "T00:00:00")
    except ValueError as e:
        raise MapperParsingError(f"failed to parse date field [{value}]") from e
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=_dt.timezone.utc)
    return int(dt.timestamp() * 1000)


def format_date_millis(millis: int) -> str:
    dt = _dt.datetime.fromtimestamp(millis / 1000.0, tz=_dt.timezone.utc)
    return dt.strftime("%Y-%m-%dT%H:%M:%S.") + f"{dt.microsecond // 1000:03d}Z"


def parse_ip_long(value: Any) -> int:
    """IPs are stored as a single int64 doc value with an
    order-preserving encoding: every v4 address sits in the negative
    range (``int(addr) - 2^32``), every v6 address in the non-negative
    one, so v4 < ``::`` < the whole v6 space and each family keeps its
    natural order.  The 128-bit v6 form is monotone-compressed: values
    below 2^49 (the low v6 space, including v4-mapped ``::ffff:0:0/96``
    literals) keep full precision; higher v6 addresses keep their top
    62 bits (range comparisons there are coarse — exact term matches
    ride the inverted index, which keeps the canonical string)."""
    addr = ipaddress.ip_address(str(value))
    if addr.version == 4:
        return int(addr) - (1 << 32)
    v = int(addr)
    if v < (1 << 49):
        return v
    return (1 << 49) + (v >> 66)


_LONG_RANGE = {
    "long": (-(2**63), 2**63 - 1),
    "integer": (-(2**31), 2**31 - 1),
    "short": (-(2**15), 2**15 - 1),
    "byte": (-128, 127),
}


class FieldType:
    """Base field type.  Subclasses override the class attrs + converters."""

    type_name = "base"
    dv_kind = "none"  # long | double | ordinal | none
    indexed = True  # produces inverted-index terms

    def __init__(self, name: str, params: Optional[dict] = None):
        self.name = name
        self.params = params or {}
        self.boost = float(self.params.get("boost", 1.0))
        self.doc_values_enabled = bool(self.params.get("doc_values", True))
        self.index_enabled = bool(self.params.get("index", True))
        self.store = bool(self.params.get("store", False))

    # --- indexing --------------------------------------------------------

    def index_terms(self, value: Any, analyzers) -> list[tuple[str, int]]:
        """Value -> [(term, position)] for the inverted index."""
        raise NotImplementedError

    def doc_value(self, value: Any):
        """Value -> column scalar (int for long-kind, float for double-kind,
        str for ordinal-kind)."""
        return None

    # --- query time ------------------------------------------------------

    def term_for_query(self, value: Any) -> str:
        """Literal in a term query -> indexed term string."""
        return str(value)

    def range_bound(self, value: Any):
        """Literal in a range query -> comparable column scalar."""
        raise IllegalArgumentError(f"field [{self.name}] of type [{self.type_name}] does not support range queries")

    def to_mapping(self) -> dict:
        return {"type": self.type_name, **{k: v for k, v in self.params.items()}}


class TextFieldType(FieldType):
    type_name = "text"
    dv_kind = "none"

    def __init__(self, name, params=None):
        super().__init__(name, params)
        self.analyzer_name = self.params.get("analyzer", "standard")
        self.search_analyzer_name = self.params.get("search_analyzer", self.analyzer_name)

    def index_terms(self, value, analyzers):
        if value is None:
            return []
        analyzer = analyzers.get(self.analyzer_name)
        return [(t.term, t.position) for t in analyzer.analyze(str(value))]

    def search_terms(self, value, analyzers) -> list[str]:
        analyzer = analyzers.get(self.search_analyzer_name)
        return analyzer.terms(str(value))


class KeywordFieldType(FieldType):
    type_name = "keyword"
    dv_kind = "ordinal"

    def __init__(self, name, params=None):
        super().__init__(name, params)
        self.ignore_above = int(self.params.get("ignore_above", 2**31 - 1))

    def index_terms(self, value, analyzers):
        if value is None:
            return []
        s = str(value)
        if len(s) > self.ignore_above:
            return []
        return [(s, 0)]

    def doc_value(self, value):
        if value is None:
            return None
        s = str(value)
        return None if len(s) > self.ignore_above else s

    def range_bound(self, value):
        return str(value)


class _NumericFieldType(FieldType):
    def _coerce(self, value):
        raise NotImplementedError

    def index_terms(self, value, analyzers):
        # Numerics are matched via doc-value columns (the Lucene points
        # analog), not postings; term/terms queries on them compare columns.
        return []

    def doc_value(self, value):
        return None if value is None else self._coerce(value)

    def term_for_query(self, value):
        return self._coerce(value)

    def range_bound(self, value):
        return self._coerce(value)


class LongFieldType(_NumericFieldType):
    type_name = "long"
    dv_kind = "long"

    def _coerce(self, value):
        if isinstance(value, bool):
            raise MapperParsingError(f"cannot coerce boolean to [{self.type_name}] for field [{self.name}]")
        try:
            f = float(value)
        except (TypeError, ValueError) as e:
            raise MapperParsingError(f"failed to parse field [{self.name}] of type [{self.type_name}]: [{value}]") from e
        if math.isnan(f) or math.isinf(f):
            raise MapperParsingError(f"[{self.name}] cannot index [{value}]")
        v = int(f)
        lo, hi = _LONG_RANGE.get(self.type_name, _LONG_RANGE["long"])
        if not (lo <= v <= hi):
            raise MapperParsingError(f"value [{value}] out of range for [{self.type_name}] field [{self.name}]")
        return v


class IntegerFieldType(LongFieldType):
    type_name = "integer"


class ShortFieldType(LongFieldType):
    type_name = "short"


class ByteFieldType(LongFieldType):
    type_name = "byte"


class DoubleFieldType(_NumericFieldType):
    type_name = "double"
    dv_kind = "double"

    def _coerce(self, value):
        if isinstance(value, bool):
            raise MapperParsingError(f"cannot coerce boolean to [{self.type_name}] for field [{self.name}]")
        try:
            return float(value)
        except (TypeError, ValueError) as e:
            raise MapperParsingError(f"failed to parse field [{self.name}] of type [{self.type_name}]: [{value}]") from e


class FloatFieldType(DoubleFieldType):
    type_name = "float"


class HalfFloatFieldType(DoubleFieldType):
    type_name = "half_float"


class ScaledFloatFieldType(_NumericFieldType):
    """reference: modules/mapper-extras ScaledFloatFieldMapper — stored as
    long = round(value * scaling_factor)."""

    type_name = "scaled_float"
    dv_kind = "long"

    def __init__(self, name, params=None):
        super().__init__(name, params)
        self.scaling_factor = float(self.params.get("scaling_factor", 1.0))

    def _coerce(self, value):
        return round(float(value) * self.scaling_factor)


class BooleanFieldType(FieldType):
    type_name = "boolean"
    dv_kind = "long"

    def _coerce(self, value) -> int:
        if isinstance(value, bool):
            return int(value)
        s = str(value).strip().lower()
        if s == "true":
            return 1
        if s in ("false", ""):
            return 0
        raise MapperParsingError(f"failed to parse boolean field [{self.name}]: [{value}]")

    def index_terms(self, value, analyzers):
        if value is None:
            return []
        return [("T" if self._coerce(value) else "F", 0)]

    def doc_value(self, value):
        return None if value is None else self._coerce(value)

    def term_for_query(self, value):
        return "T" if self._coerce(value) else "F"

    def range_bound(self, value):
        return self._coerce(value)


class DateFieldType(FieldType):
    type_name = "date"
    dv_kind = "long"

    def _parse(self, value):
        fmt = str(self.params.get("format", ""))
        if "epoch_second" in fmt and isinstance(value, (int, float)) \
                or "epoch_second" in fmt and str(value).lstrip(
                    "-").isdigit():
            return int(float(value) * 1000)
        return parse_date_millis(value)

    def index_terms(self, value, analyzers):
        return []

    def doc_value(self, value):
        return None if value is None else self._parse(value)

    def term_for_query(self, value):
        return self._parse(value)

    def range_bound(self, value):
        return self._parse(value)


class IpFieldType(FieldType):
    type_name = "ip"
    dv_kind = "long"

    def index_terms(self, value, analyzers):
        if value is None:
            return []
        return [(str(ipaddress.ip_address(str(value))), 0)]

    def doc_value(self, value):
        return None if value is None else parse_ip_long(value)

    def range_bound(self, value):
        # CIDR bounds are handled by the query layer expanding to a range.
        return parse_ip_long(value)


class DenseVectorFieldType(FieldType):
    """k-NN vector field (the out-of-tree opensearch-knn plugin's
    ``knn_vector``; we accept both ``dense_vector`` and ``knn_vector``)."""

    type_name = "dense_vector"
    dv_kind = "vector"
    indexed = False

    def __init__(self, name, params=None):
        super().__init__(name, params)
        self.dims = int(self.params.get("dims") or self.params.get("dimension") or 0)
        if self.dims <= 0:
            raise MapperParsingError(f"dense_vector field [{name}] requires [dims]")
        # space_type may live at the top level (newer knn_vector
        # mappings) or inside [method] (the opensearch-knn plugin's
        # historical shape) — honor both, top level winning
        space = (self.params.get("space_type")
                 or self.params.get("similarity")
                 or (self.params.get("method") or {}).get("space_type")
                 or "l2")
        self.space_type = {"l2_norm": "l2", "dot_product": "innerproduct", "cosine": "cosinesimil"}.get(space, space)
        # ANN method definition (the opensearch-knn plugin's mapping shape:
        # {"name": "ivf"|"ivf_pq", "parameters": {nlist, nprobe, m}});
        # absent -> exact brute force
        method = self.params.get("method")
        if method is not None:
            name = (method.get("name") or "").lower()
            if name not in ("ivf", "ivf_pq", "flat", "exact"):
                raise MapperParsingError(
                    f"unknown knn method [{name}] for field "
                    f"[{self.name}] — supported: ivf, ivf_pq, flat")
            self.method = {"name": name,
                           **(method.get("parameters") or {})}
            if name == "ivf_pq":
                m = int(self.method.get("m", 8))
                if m <= 0 or self.dims % m != 0:
                    raise MapperParsingError(
                        f"ivf_pq [m]=[{m}] must divide [dims]="
                        f"[{self.dims}] for field [{self.name}]")
        else:
            self.method = None

    def index_terms(self, value, analyzers):
        return []

    def doc_value(self, value):
        if value is None:
            return None
        vec = [float(x) for x in value]
        if len(vec) != self.dims:
            raise MapperParsingError(
                f"vector length [{len(vec)}] does not match [dims]=[{self.dims}] for field [{self.name}]"
            )
        return vec


class GeoPointFieldType(FieldType):
    """Stored as two float64 columns (lat, lon); distance filters/aggs are
    vectorized haversine over the columns (reference: GeoPointFieldMapper)."""

    type_name = "geo_point"
    dv_kind = "geo_point"

    def index_terms(self, value, analyzers):
        return []

    def doc_value(self, value):
        if value is None:
            return None
        if isinstance(value, dict):
            return (float(value["lat"]), float(value["lon"]))
        if isinstance(value, str):
            if "," in value:
                lat, lon = value.split(",")
                return (float(lat), float(lon))
            raise MapperParsingError(f"geohash not supported for field [{self.name}]")
        if isinstance(value, (list, tuple)):  # GeoJSON order [lon, lat]
            return (float(value[1]), float(value[0]))
        raise MapperParsingError(f"cannot parse geo_point [{value}]")


class PercolatorFieldType(FieldType):
    """Stores a query for reverse search (the percolator module's
    ``percolator`` field; ref modules/percolator).  The raw query JSON
    lives in _source; parse-time validation rejects malformed queries at
    index time like PercolatorFieldMapper does."""

    type_name = "percolator"
    dv_kind = "none"
    indexed = True     # produces no terms, but index-time validation runs
    allow_multiple = False   # one query per doc (PercolatorFieldMapper)

    def index_terms(self, value, analyzers):
        from opensearch_tpu.search.query_dsl import parse_query
        if value is not None:
            parse_query(value)         # validate eagerly; raises 400
        return []

    def doc_value(self, value):
        return None


class NestedFieldType(FieldType):
    """nested object container (the reference's ObjectMapper nested=true;
    each element of the array is matched as its own unit by the nested
    query — ref index/mapper/ + join/ToParentBlockJoinQuery).  The field
    itself indexes nothing; its child paths carry object-major columns
    (index/segment.py NestedBlock)."""

    type_name = "nested"
    dv_kind = "nested"
    indexed = False

    def index_terms(self, value, analyzers):
        return []

    def doc_value(self, value):
        return None


class JoinFieldType(FieldType):
    """Parent-join field (ref modules/parent-join/
    ParentJoinFieldMapper.java).  A doc's value is either a relation
    name ("question") or {"name": "answer", "parent": "<parent _id>"}.
    The mapper writes two hidden ordinal columns — ``<field>#name``
    (relation) and ``<field>#parent`` (the parent join key) — which
    has_child / has_parent / parent_id join host-side across segments
    (the global-ordinals OrdinalMap role)."""

    type_name = "join"
    dv_kind = "none"
    indexed = False
    allow_multiple = False

    def __init__(self, name, params=None):
        super().__init__(name, params)
        rel = self.params.get("relations") or {}
        # parent -> [children]
        self.relations = {p: (c if isinstance(c, list) else [c])
                          for p, c in rel.items()}

    def parent_of(self, child_type: str):
        for p, cs in self.relations.items():
            if child_type in cs:
                return p
        return None

    def is_relation(self, name: str) -> bool:
        return name in self.relations or self.parent_of(name) is not None

    def index_terms(self, value, analyzers):
        return []


class RankFeatureFieldType(FieldType):
    """Positive per-doc feature for rank_feature queries
    (mapper-extras RankFeatureFieldMapper): a double doc value; values
    must be strictly positive."""

    type_name = "rank_feature"
    dv_kind = "double"
    indexed = False
    allow_multiple = False

    def index_terms(self, value, analyzers):
        return []

    def doc_value(self, value):
        try:
            v = float(value)
        except (TypeError, ValueError) as e:
            raise MapperParsingError(
                f"failed to parse field [{self.name}] of type "
                f"[rank_feature]: [{value}]") from e
        if not math.isfinite(v) or v <= 0:
            raise MapperParsingError(
                f"[rank_feature] field [{self.name}] requires a positive "
                f"finite value, got [{value}]")
        if self.params.get("positive_score_impact") is False:
            # negative-impact features store the reciprocal, like the
            # reference's freq encoding
            v = 1.0 / v
        return v


class CompletionFieldType(FieldType):
    """Prefix completion (suggest/completion/CompletionFieldMapper).
    Inputs live in the segment's SORTED ordinal column, so a prefix is a
    binary-searched ordinal range — the array-native stand-in for the
    reference's FST; weights ride a parallel numeric column."""

    type_name = "completion"
    dv_kind = "ordinal"
    indexed = False

    def doc_value(self, value):
        return str(value)

    def index_terms(self, value, analyzers):
        return []


class ObjectFieldType(FieldType):
    """Explicit ``type: object`` container: no terms/doc-values of its
    own — its sub-fields are mapped flattened as ``parent.child``
    (ObjectMapper)."""

    type_name = "object"
    dv_kind = "none"
    indexed = False

    def index_terms(self, value, analyzers):
        return []


class BinaryFieldType(FieldType):
    """base64 blob: kept in _source, not term-searchable.  A constant
    presence marker is indexed per valued doc so ``exists`` works (the
    reference tracks the same via _field_names — BinaryFieldMapper)."""

    type_name = "binary"
    dv_kind = "none"
    indexed = True          # only the presence marker below

    def index_terms(self, value, analyzers):
        return [] if value is None else [("\x01present", 0)]


class UnsignedLongFieldType(FieldType):
    """64-bit unsigned integer (opensearch's unsigned_long).  Values are
    stored raw in the int64 column; the upper half-range [2^63, 2^64)
    saturates to 2^63-1 (ordering preserved, exact values above 2^63
    are not distinguished — the reference's full-range support would
    need an unsigned column type)."""

    type_name = "unsigned_long"
    dv_kind = "long"
    indexed = True

    _MAX_I64 = (1 << 63) - 1

    def index_terms(self, value, analyzers):
        return []

    def _clamp(self, value) -> int:
        v = int(value)
        if not (0 <= v < (1 << 64)):
            raise IllegalArgumentError(
                f"Value [{value}] is out of range for an unsigned long")
        return min(v, self._MAX_I64)

    def doc_value(self, value):
        return self._clamp(value)

    def term_for_query(self, value):
        return self._clamp(value)

    def range_bound(self, value):
        return self._clamp(value)


class DateNanosFieldType(DateFieldType):
    """date_nanos: stored at millisecond precision in the same int64
    column (the reference keeps nanos; sub-millisecond precision is not
    distinguished here — documented divergence)."""

    type_name = "date_nanos"


FIELD_TYPES = {
    cls.type_name: cls
    for cls in [
        NestedFieldType, PercolatorFieldType,
        TextFieldType, KeywordFieldType, LongFieldType, IntegerFieldType,
        ShortFieldType, ByteFieldType, DoubleFieldType, FloatFieldType,
        HalfFloatFieldType, ScaledFloatFieldType, BooleanFieldType,
        DateFieldType, IpFieldType, DenseVectorFieldType, GeoPointFieldType,
        BinaryFieldType, UnsignedLongFieldType, ObjectFieldType,
        JoinFieldType, CompletionFieldType, RankFeatureFieldType,
        DateNanosFieldType,
    ]
}
FIELD_TYPES["knn_vector"] = DenseVectorFieldType


def build_field_type(name: str, config: dict) -> FieldType:
    type_name = config.get("type")
    if type_name is None:
        raise MapperParsingError(f"no type specified for field [{name}]")
    cls = FIELD_TYPES.get(type_name)
    if cls is None:
        raise MapperParsingError(f"No handler for type [{type_name}] declared on field [{name}]")
    return cls(name, {k: v for k, v in config.items() if k not in ("type", "fields", "properties")})
