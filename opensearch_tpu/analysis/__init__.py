from opensearch_tpu.analysis.registry import AnalysisRegistry, Analyzer, Token  # noqa: F401
