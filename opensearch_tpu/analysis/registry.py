"""Analysis chain: char filters -> tokenizer -> token filters -> tokens.

Analog of the reference's AnalysisRegistry / AnalysisModule
(index/analysis/AnalysisRegistry.java, indices/analysis/AnalysisModule.java)
with the built-in analyzers from core + modules/analysis-common that matter
for the BASELINE workloads: standard, simple, whitespace, keyword, stop,
english.  Custom analyzers compose named tokenizers/filters from mapping
settings, the same way ``analysis.analyzer.my.type: custom`` does.

Tokens carry positions (for phrase queries) and offsets (for highlighting).
Analysis is pure host-side string work — it never touches the device.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Callable, Iterable, Optional

from opensearch_tpu.analysis import porter
from opensearch_tpu.common.errors import IllegalArgumentError


@dataclass
class Token:
    term: str
    position: int
    start_offset: int
    end_offset: int


# Unicode-ish word tokenization: runs of word chars incl. digits; keeps
# interior apostrophes out (standard tokenizer splits possessives anyway via
# english filters; close enough to UAX#29 for the conformance bar we target).
_STANDARD_RE = re.compile(r"[\w][\w]*", re.UNICODE)
_LETTER_RE = re.compile(r"[^\W\d_]+", re.UNICODE)
_WHITESPACE_RE = re.compile(r"\S+")

# Lucene EnglishAnalyzer.ENGLISH_STOP_WORDS_SET
ENGLISH_STOP_WORDS = frozenset(
    "a an and are as at be but by for if in into is it no not of on or such"
    " that the their then there these they this to was will with".split()
)


def _regex_tokenizer(pattern: re.Pattern) -> Callable[[str], list[Token]]:
    def tokenize(text: str) -> list[Token]:
        return [
            Token(m.group(), pos, m.start(), m.end())
            for pos, m in enumerate(pattern.finditer(text))
        ]

    return tokenize


def _keyword_tokenizer(text: str) -> list[Token]:
    return [Token(text, 0, 0, len(text))] if text else []


def _ngram_tokenizer(min_gram: int, max_gram: int, edge: bool = False) -> Callable[[str], list[Token]]:
    def tokenize(text: str) -> list[Token]:
        out = []
        pos = 0
        for n in range(min_gram, max_gram + 1):
            if n > len(text):
                break
            upper = 1 if edge else len(text) - n + 1
            for i in range(0, max(0, upper)):
                out.append(Token(text[i : i + n], pos, i, i + n))
                pos += 1
        return out

    return tokenize


def _pattern_split_tokenizer(pattern: str) -> Callable[[str], list[Token]]:
    """OpenSearch ``pattern`` tokenizer: the pattern is the *separator*."""
    sep = re.compile(pattern)

    def tokenize(text: str) -> list[Token]:
        out = []
        pos = 0
        last = 0
        for m in sep.finditer(text):
            if m.start() > last:
                out.append(Token(text[last : m.start()], pos, last, m.start()))
                pos += 1
            last = m.end()
        if last < len(text):
            out.append(Token(text[last:], pos, last, len(text)))
        return out

    return tokenize


def _build_tokenizer(name: str, tcfg: dict) -> Callable[[str], list[Token]]:
    ttype = tcfg.get("type", name)
    if ttype in ("ngram", "nGram", "edge_ngram", "edgeNGram"):
        return _ngram_tokenizer(
            int(tcfg.get("min_gram", 1)),
            int(tcfg.get("max_gram", 2)),
            edge=ttype in ("edge_ngram", "edgeNGram"),
        )
    if ttype == "pattern":
        return _pattern_split_tokenizer(tcfg.get("pattern", r"\W+"))
    if ttype in TOKENIZERS:
        return TOKENIZERS[ttype]
    raise IllegalArgumentError(f"unknown tokenizer type [{ttype}]")


TOKENIZERS: dict[str, Callable] = {
    "standard": _regex_tokenizer(_STANDARD_RE),
    "letter": _regex_tokenizer(_LETTER_RE),
    "whitespace": _regex_tokenizer(_WHITESPACE_RE),
    "keyword": _keyword_tokenizer,
}


# --- token filters ---------------------------------------------------------


def lowercase_filter(tokens: Iterable[Token]) -> list[Token]:
    return [Token(t.term.lower(), t.position, t.start_offset, t.end_offset) for t in tokens]


def stop_filter(stopwords=ENGLISH_STOP_WORDS):
    def apply(tokens: Iterable[Token]) -> list[Token]:
        # Positions are preserved (gaps where stopwords were), matching
        # Lucene's StopFilter with enablePositionIncrements.
        return [t for t in tokens if t.term not in stopwords]

    return apply


def porter_stem_filter(tokens: Iterable[Token]) -> list[Token]:
    return [Token(porter.stem(t.term), t.position, t.start_offset, t.end_offset) for t in tokens]


def possessive_english_filter(tokens: Iterable[Token]) -> list[Token]:
    out = []
    for t in tokens:
        term = t.term
        if term.endswith("'s") or term.endswith("’s"):
            term = term[:-2]
        out.append(Token(term, t.position, t.start_offset, t.end_offset))
    return out


def asciifolding_filter(tokens: Iterable[Token]) -> list[Token]:
    import unicodedata

    out = []
    for t in tokens:
        folded = unicodedata.normalize("NFKD", t.term).encode("ascii", "ignore").decode()
        out.append(Token(folded or t.term, t.position, t.start_offset, t.end_offset))
    return out


def _length_filter(min_len: int, max_len: int):
    def apply(tokens):
        return [t for t in tokens if min_len <= len(t.term) <= max_len]

    return apply


def _shingle_filter(min_size: int = 2, max_size: int = 2, sep: str = " "):
    def apply(tokens: list[Token]) -> list[Token]:
        out = list(tokens)
        for size in range(min_size, max_size + 1):
            for i in range(0, len(tokens) - size + 1):
                window = tokens[i : i + size]
                out.append(
                    Token(
                        sep.join(t.term for t in window),
                        window[0].position,
                        window[0].start_offset,
                        window[-1].end_offset,
                    )
                )
        return out

    return apply


TOKEN_FILTERS: dict[str, Callable] = {
    "lowercase": lambda cfg: lowercase_filter,
    "stop": lambda cfg: stop_filter(frozenset(cfg.get("stopwords", ENGLISH_STOP_WORDS))),
    "porter_stem": lambda cfg: porter_stem_filter,
    "stemmer": lambda cfg: porter_stem_filter,
    "asciifolding": lambda cfg: asciifolding_filter,
    "possessive_english": lambda cfg: possessive_english_filter,
    "length": lambda cfg: _length_filter(int(cfg.get("min", 0)), int(cfg.get("max", 1 << 30))),
    "shingle": lambda cfg: _shingle_filter(
        int(cfg.get("min_shingle_size", 2)), int(cfg.get("max_shingle_size", 2))
    ),
}

# --- char filters ----------------------------------------------------------

CHAR_FILTERS: dict[str, Callable] = {
    "html_strip": lambda cfg: (lambda text: re.sub(r"<[^>]*>", " ", text)),
}


class Analyzer:
    def __init__(self, name: str, tokenizer: Callable, filters: list[Callable], char_filters=()):
        self.name = name
        self.tokenizer = tokenizer
        self.filters = list(filters)
        self.char_filters = list(char_filters)

    def analyze(self, text: str) -> list[Token]:
        for cf in self.char_filters:
            text = cf(text)
        tokens = self.tokenizer(text)
        for f in self.filters:
            tokens = f(tokens)
        return tokens

    def terms(self, text: str) -> list[str]:
        return [t.term for t in self.analyze(text)]


def _builtin_analyzers() -> dict[str, Analyzer]:
    std = TOKENIZERS["standard"]
    return {
        "standard": Analyzer("standard", std, [lowercase_filter]),
        "simple": Analyzer("simple", TOKENIZERS["letter"], [lowercase_filter]),
        "whitespace": Analyzer("whitespace", TOKENIZERS["whitespace"], []),
        "keyword": Analyzer("keyword", _keyword_tokenizer, []),
        "stop": Analyzer("stop", TOKENIZERS["letter"], [lowercase_filter, stop_filter()]),
        "english": Analyzer(
            "english",
            std,
            [possessive_english_filter, lowercase_filter, stop_filter(), porter_stem_filter],
        ),
    }


class AnalysisRegistry:
    """Per-index registry resolving analyzer names, incl. custom analyzers
    declared under ``settings.analysis`` (AnalysisRegistry.java analog)."""

    def __init__(self, analysis_settings: Optional[dict] = None):
        self._analyzers = _builtin_analyzers()
        cfg = analysis_settings or {}
        custom_tokenizers: dict[str, Callable] = {}
        for name, tcfg in (cfg.get("tokenizer") or {}).items():
            custom_tokenizers[name] = _build_tokenizer(name, tcfg)
        custom_filters: dict[str, Callable] = {}
        for name, fcfg in (cfg.get("filter") or {}).items():
            ftype = fcfg.get("type", name)
            factory = TOKEN_FILTERS.get(ftype)
            if factory is None:
                raise IllegalArgumentError(f"unknown token filter type [{ftype}]")
            custom_filters[name] = factory(fcfg)
        for name, acfg in (cfg.get("analyzer") or {}).items():
            atype = acfg.get("type", "custom")
            if atype != "custom":
                if atype in self._analyzers:
                    self._analyzers[name] = self._analyzers[atype]
                    continue
                raise IllegalArgumentError(f"unknown analyzer type [{atype}]")
            tok_name = acfg.get("tokenizer", "standard")
            tokenizer = custom_tokenizers.get(tok_name) or TOKENIZERS.get(tok_name)
            if tokenizer is None:
                # built-in parameterized tokenizer named directly on the
                # analyzer (ngram/edge_ngram/pattern), params inline
                tokenizer = _build_tokenizer(tok_name, {**acfg, "type": tok_name})
            filters = []
            for fname in acfg.get("filter", []):
                if fname in custom_filters:
                    filters.append(custom_filters[fname])
                elif fname in TOKEN_FILTERS:
                    filters.append(TOKEN_FILTERS[fname]({}))
                else:
                    raise IllegalArgumentError(f"unknown token filter [{fname}]")
            char_filters = []
            for cname in acfg.get("char_filter", []):
                if cname in CHAR_FILTERS:
                    char_filters.append(CHAR_FILTERS[cname]({}))
                else:
                    raise IllegalArgumentError(f"unknown char filter [{cname}]")
            self._analyzers[name] = Analyzer(name, tokenizer, filters, char_filters)

    def get(self, name: str) -> Analyzer:
        analyzer = self._analyzers.get(name)
        if analyzer is None:
            raise IllegalArgumentError(f"analyzer [{name}] not found")
        return analyzer

    def names(self):
        return sorted(self._analyzers)
