"""Porter stemming algorithm (Porter, 1980) — a clean-room implementation of
the published algorithm, used by the ``english`` analyzer the way the
reference wires Lucene's PorterStemFilter
(modules/analysis-common PorterStemTokenFilterFactory)."""

from __future__ import annotations

_VOWELS = set("aeiou")


def _is_cons(word: str, i: int) -> bool:
    ch = word[i]
    if ch in _VOWELS:
        return False
    if ch == "y":
        return i == 0 or not _is_cons(word, i - 1)
    return True


def _measure(stem: str) -> int:
    """Number of VC sequences in the stem."""
    m = 0
    prev_cons = True
    started = False
    for i in range(len(stem)):
        cons = _is_cons(stem, i)
        if not cons:
            started = True
        if started and cons and not prev_cons:
            m += 1
        prev_cons = cons
    return m


def _has_vowel(stem: str) -> bool:
    return any(not _is_cons(stem, i) for i in range(len(stem)))


def _ends_double_cons(word: str) -> bool:
    return (
        len(word) >= 2
        and word[-1] == word[-2]
        and _is_cons(word, len(word) - 1)
    )


def _cvc(word: str) -> bool:
    if len(word) < 3:
        return False
    if not (
        _is_cons(word, len(word) - 3)
        and not _is_cons(word, len(word) - 2)
        and _is_cons(word, len(word) - 1)
    ):
        return False
    return word[-1] not in "wxy"


def stem(word: str) -> str:
    if len(word) <= 2:
        return word
    w = word

    # Step 1a
    if w.endswith("sses"):
        w = w[:-2]
    elif w.endswith("ies"):
        w = w[:-2]
    elif w.endswith("ss"):
        pass
    elif w.endswith("s"):
        w = w[:-1]

    # Step 1b
    flag_1b = False
    if w.endswith("eed"):
        if _measure(w[:-3]) > 0:
            w = w[:-1]
    elif w.endswith("ed") and _has_vowel(w[:-2]):
        w = w[:-2]
        flag_1b = True
    elif w.endswith("ing") and _has_vowel(w[:-3]):
        w = w[:-3]
        flag_1b = True
    if flag_1b:
        if w.endswith(("at", "bl", "iz")):
            w += "e"
        elif _ends_double_cons(w) and not w.endswith(("l", "s", "z")):
            w = w[:-1]
        elif _measure(w) == 1 and _cvc(w):
            w += "e"

    # Step 1c
    if w.endswith("y") and _has_vowel(w[:-1]):
        w = w[:-1] + "i"

    # Step 2
    step2 = [
        ("ational", "ate"), ("tional", "tion"), ("enci", "ence"), ("anci", "ance"),
        ("izer", "ize"), ("abli", "able"), ("alli", "al"), ("entli", "ent"),
        ("eli", "e"), ("ousli", "ous"), ("ization", "ize"), ("ation", "ate"),
        ("ator", "ate"), ("alism", "al"), ("iveness", "ive"), ("fulness", "ful"),
        ("ousness", "ous"), ("aliti", "al"), ("iviti", "ive"), ("biliti", "ble"),
    ]
    for suffix, repl in step2:
        if w.endswith(suffix):
            if _measure(w[: -len(suffix)]) > 0:
                w = w[: -len(suffix)] + repl
            break

    # Step 3
    step3 = [
        ("icate", "ic"), ("ative", ""), ("alize", "al"), ("iciti", "ic"),
        ("ical", "ic"), ("ful", ""), ("ness", ""),
    ]
    for suffix, repl in step3:
        if w.endswith(suffix):
            if _measure(w[: -len(suffix)]) > 0:
                w = w[: -len(suffix)] + repl
            break

    # Step 4
    step4 = [
        "al", "ance", "ence", "er", "ic", "able", "ible", "ant", "ement",
        "ment", "ent", "ion", "ou", "ism", "ate", "iti", "ous", "ive", "ize",
    ]
    for suffix in step4:
        if w.endswith(suffix):
            stem_part = w[: -len(suffix)]
            if _measure(stem_part) > 1:
                if suffix == "ion" and not stem_part.endswith(("s", "t")):
                    pass
                else:
                    w = stem_part
            break

    # Step 5a
    if w.endswith("e"):
        m = _measure(w[:-1])
        if m > 1 or (m == 1 and not _cvc(w[:-1])):
            w = w[:-1]
    # Step 5b
    if _measure(w) > 1 and _ends_double_cons(w) and w.endswith("l"):
        w = w[:-1]

    return w
