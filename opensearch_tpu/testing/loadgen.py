"""Open-loop load harness: latency-under-load curves against the real
REST edge, coordinated-omission-free (ROADMAP item 6).

Closed-loop harnesses (send, wait, send again) understate tail latency
under overload: while the server stalls, the client simply stops
offering load, so one stall charges ONE request instead of every
request that would have arrived meanwhile — coordinated omission.
This harness is open-loop:

- every request gets a *scheduled* arrival time drawn up front from a
  seeded Poisson process modulated by a deterministic diurnal/burst
  envelope (``arrival_schedule`` — Lewis/Shedler thinning, so the
  whole schedule is a pure function of (rate, duration, seed,
  envelope) and the two-run determinism tests can pin it);
- a dispatcher fires each request at its scheduled time regardless of
  how many are still in flight (backlog queues, it never gates);
- latency is charged from the SCHEDULED arrival, not the send — the
  queue time a lagging server causes IS the measurement
  (``tools/check_open_loop.py`` lints this module against
  post-send-timestamp backsliding).

Traffic comes as per-tenant **scenario packs** mapped to X-Opaque-Id
tenants (the PR-14 QoS tenant key): zipf lexical head/tail search
(sharing ``zipf_query_log`` with the soak harness and bench.py),
RAG/hybrid kNN, analytics aggregations, sorted paging walks, and
bulk-ingest side traffic.  Each pack's outcome ledger (ok / 429 with
Retry-After honored / partial / 5xx) is cross-checked against the
node's own ``_nodes/stats`` admission tenants block and the insights
per-tenant rollups (``qos.check_tenant_attribution``).

``LoadgenRunner.sweep`` walks offered-load points to produce the
latency-under-load curve (p50/p99/p999 vs offered qps per pack) and a
measured ``max_sustainable_qps`` per pack; ``run_latency_under_load``
is the boot-a-node-and-sweep entry bench.py's ``latency_under_load``
phase and the tests share.  429 responses are retried no earlier than
their Retry-After hint plus seeded jitter, and per-tenant hint
presence is a recorded verdict — a 429 without a hint is a bug this
harness exists to catch.
"""

from __future__ import annotations

import math
import os
import random
import threading
import time
import zlib
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional

import numpy as np

from opensearch_tpu.testing.workload import corpus_doc, zipf_query_log

#: default index + vector geometry for the seeded corpus
LOAD_INDEX = "loadgen"
VEC_DIM = 8

_TWO_PI = 2.0 * math.pi


# -- arrival processes ------------------------------------------------------

def _flat(u: float) -> float:
    return 1.0


def _diurnal(u: float) -> float:
    """One sinusoidal 'day' across the run: trough 0.55x, peak 1.0x."""
    return 0.55 + 0.225 * (1.0 - math.cos(_TWO_PI * u))


def _burst(u: float) -> float:
    """Four square-wave bursts across the run: 1.0x inside a burst
    window, 0.4x between them."""
    return 1.0 if (u * 4.0) % 1.0 < 0.25 else 0.4


#: name -> (intensity over run-phase u in [0,1), analytic mean) — the
#: mean normalizes thinning so the realized average rate equals the
#: offered rate whatever the envelope shape
ENVELOPES: dict = {"flat": (_flat, 1.0),
                   "diurnal": (_diurnal, 0.775),
                   "burst": (_burst, 0.55)}


def arrival_schedule(rate_qps: float, duration_s: float, seed: int,
                     envelope: str = "flat") -> list:
    """Sorted scheduled-arrival offsets (seconds) for one pack: a
    homogeneous Poisson process at the envelope-normalized peak rate,
    thinned by the deterministic envelope (Lewis/Shedler), so the mean
    realized rate is ``rate_qps`` and the schedule is a pure function
    of its arguments."""
    try:
        fn, mean = ENVELOPES[envelope]
    except KeyError:
        raise ValueError(f"unknown arrival envelope [{envelope}]; one "
                         f"of {sorted(ENVELOPES)}") from None
    if rate_qps <= 0 or duration_s <= 0:
        return []
    rng = random.Random(seed)
    peak = rate_qps / mean
    out, t = [], 0.0
    while True:
        t += rng.expovariate(peak)
        if t >= duration_s:
            break
        if rng.random() <= fn(t / duration_s):
            out.append(round(t, 9))
    return out


# -- scenario packs ---------------------------------------------------------

class ScenarioPack:
    """One tenant's traffic shape: a weight (its share of the total
    offered qps), an arrival envelope, and a seeded request generator.
    ``requests(seed, n)`` is a pure function — the determinism tests
    pin the sequence."""

    def __init__(self, name: str, tenant: str, weight: float,
                 envelope: str, gen: Callable, *,
                 searchish: bool = True):
        self.name = name
        self.tenant = tenant
        self.weight = float(weight)
        self.envelope = envelope
        self._gen = gen
        #: search-path traffic holds admission permits and lands in
        #: insights; bulk side-traffic does neither
        self.searchish = searchish

    def stream_seed(self, seed: int) -> int:
        """Per-pack derived seed: stable across processes (crc32, not
        ``hash``, which is salted per interpreter)."""
        return (int(seed) << 16) ^ zlib.crc32(self.name.encode())

    def requests(self, seed: int, n: int) -> list:
        return self._gen(random.Random(self.stream_seed(seed)), n)


def _lexical_gen(index: str, vocab_size: int) -> Callable:
    def gen(rng: random.Random, n: int) -> list:
        pairs = zipf_query_log(n, vocab_size, seed=rng.randrange(2**31))
        out = []
        for a, b in pairs:
            body = {"query": {"match": {"body": f"t{a} t{b}"}},
                    "size": 10}
            if rng.random() < 0.5:
                # head traffic rarely needs exact totals — and
                # track_total_hits:false arms the kth block-max prune
                body["track_total_hits"] = False
            out.append({"op": "search", "index": index, "body": body})
        return out
    return gen


def _rag_gen(index: str, vocab_size: int, dim: int) -> Callable:
    def gen(rng: random.Random, n: int) -> list:
        out = []
        for _ in range(n):
            t = min(int(rng.paretovariate(1.3)) - 1, vocab_size - 1)
            qv = [round(rng.random(), 4) for _ in range(dim)]
            out.append({"op": "search", "index": index, "body": {
                "query": {"hybrid": {"queries": [
                    {"match": {"body": f"t{t}"}},
                    {"knn": {"vec": {"vector": qv, "k": 10}}}]}},
                "size": 10}})
        return out
    return gen


def _analytics_gen(index: str, n_docs: int) -> Callable:
    def gen(rng: random.Random, n: int) -> list:
        out = []
        for _ in range(n):
            if rng.random() < 0.5:
                aggs = {"per_hour": {"date_histogram": {
                    "field": "ts", "fixed_interval": "1h"}}}
            else:
                aggs = {"tags": {"terms": {"field": "tag", "size": 8}}}
            lo = rng.randrange(max(n_docs, 1))
            out.append({"op": "search", "index": index, "body": {
                "size": 0, "aggs": aggs,
                "query": {"range": {"v": {"gte": lo // 2}}}}})
        return out
    return gen


def _paging_gen(index: str, n_docs: int, pages: int = 3,
                page_size: int = 10) -> Callable:
    def gen(rng: random.Random, n: int) -> list:
        out = []
        page, start = 0, 0
        for _ in range(n):
            if page == 0:
                start = rng.randrange(
                    max(n_docs - pages * page_size, 1))
            out.append({"op": "search", "index": index, "body": {
                "query": {"match_all": {}}, "sort": [{"v": "asc"}],
                "from": start + page * page_size, "size": page_size}})
            page = (page + 1) % pages
        return out
    return gen


def _bulk_gen(index: str, vocab_size: int, dim: int,
              batch: int = 4) -> Callable:
    tags = [f"tag{i}" for i in range(8)]

    def gen(rng: random.Random, n: int) -> list:
        out = []
        for i in range(n):
            docs = []
            for j in range(batch):
                doc_seed = rng.randrange(2**31)
                src = corpus_doc(doc_seed, j, vocab_size, tags)
                vrng = random.Random(doc_seed ^ 0x5EC)
                src["vec"] = [round(vrng.random(), 4)
                              for _ in range(dim)]
                docs.append((f"lg-{i}-{j}", src))
            out.append({"op": "bulk", "index": index, "docs": docs})
        return out
    return gen


def default_packs(*, index: str = LOAD_INDEX, vocab_size: int = 2000,
                  n_docs: int = 600, dim: int = VEC_DIM) -> list:
    """The standard per-tenant scenario-pack set: zipf lexical head/
    tail traffic (BM25S-style; shares ``zipf_query_log`` with bench.py
    and the soak), RAG/hybrid kNN term-bags, analytics aggregations,
    sorted paging walks, and bulk-ingest side traffic."""
    return [
        ScenarioPack("zipf_lexical", "lg-lexical", 4.0, "diurnal",
                     _lexical_gen(index, vocab_size)),
        ScenarioPack("rag_hybrid", "lg-rag", 2.0, "flat",
                     _rag_gen(index, vocab_size, dim)),
        ScenarioPack("analytics_aggs", "lg-analytics", 1.0, "flat",
                     _analytics_gen(index, n_docs)),
        ScenarioPack("paging_walk", "lg-paging", 1.0, "burst",
                     _paging_gen(index, n_docs)),
        ScenarioPack("bulk_ingest", "lg-ingest", 1.0, "burst",
                     _bulk_gen(index, vocab_size, dim),
                     searchish=False),
    ]


# -- corpus -----------------------------------------------------------------

def corpus_docs(n_docs: int, *, seed: int = 42, vocab_size: int = 2000,
                dim: int = VEC_DIM) -> list:
    """Deterministic corpus: the soak harness's doc shape
    (``workload.corpus_doc``) plus a seeded ``vec`` kNN field for the
    RAG pack."""
    tags = [f"tag{i}" for i in range(8)]
    out = []
    for i in range(n_docs):
        src = corpus_doc(seed, i, vocab_size, tags)
        vrng = random.Random((seed << 21) ^ i ^ 0x5EC)
        src["vec"] = [round(vrng.random(), 4) for _ in range(dim)]
        out.append((f"d{i}", src))
    return out


def seed_corpus(client, *, index: str = LOAD_INDEX, n_docs: int = 600,
                seed: int = 42, vocab_size: int = 2000,
                dim: int = VEC_DIM, shards: int = 1,
                chunk: int = 200) -> int:
    """Create the loadgen index over REST and bulk-load the seeded
    corpus; returns the doc count."""
    client.indices.create(index, {
        "settings": {"number_of_shards": shards,
                     "number_of_replicas": 0},
        "mappings": {"properties": {
            "body": {"type": "text"},
            "ts": {"type": "date"},
            "tag": {"type": "keyword"},
            "v": {"type": "long"},
            "vec": {"type": "knn_vector", "dimension": dim}}}})
    docs = corpus_docs(n_docs, seed=seed, vocab_size=vocab_size,
                       dim=dim)
    for start in range(0, len(docs), chunk):
        lines: list = []
        for doc_id, src in docs[start:start + chunk]:
            lines.append({"index": {"_id": doc_id}})
            lines.append(src)
        client.bulk(lines, index=index)
    client.indices.refresh(index)
    return len(docs)


# -- execution --------------------------------------------------------------

class RestExecutor:
    """Executes pack ops against a node's real HTTP edge via the
    bundled client — one client per tenant so every request carries
    that tenant's ``X-Opaque-Id`` default header.  Returns the
    harness's outcome dict: status, Retry-After hint (the client
    surfaces the response header on 429 errors), partial flag."""

    def __init__(self, base_url: str, timeout: float = 30.0):
        self._base = base_url
        self._timeout = timeout
        self._clients: dict = {}
        self._lock = threading.Lock()

    def client(self, tenant: str):
        from opensearch_tpu.client import OpenSearch
        with self._lock:
            cli = self._clients.get(tenant)
            if cli is None:
                cli = OpenSearch([self._base], timeout=self._timeout,
                                 headers={"X-Opaque-Id": tenant})
                self._clients[tenant] = cli
            return cli

    def __call__(self, op: dict, tenant: str) -> dict:
        from opensearch_tpu.client import ConnectionError as CliConnError
        from opensearch_tpu.client import TransportError
        cli = self.client(tenant)
        try:
            if op["op"] == "search":
                resp = cli.search(index=op["index"], body=op["body"])
                shards = resp.get("_shards") or {}
                return {"status": 200,
                        "partial": bool(shards.get("failed"))}
            if op["op"] == "bulk":
                lines: list = []
                for doc_id, src in op["docs"]:
                    lines.append({"index": {"_id": doc_id}})
                    lines.append(src)
                resp = cli.bulk(lines, index=op["index"])
                return {"status": 200,
                        "partial": bool(resp.get("errors"))}
            raise ValueError(f"unknown loadgen op [{op['op']}]")
        except CliConnError:
            return {"status": 599}
        except TransportError as e:
            status = e.status_code if isinstance(e.status_code, int) \
                else 599
            return {"status": status,
                    "retry_after": getattr(e, "retry_after", None)}


# -- the runner -------------------------------------------------------------

class LoadgenRunner:
    """Open-loop sweep driver.  ``execute(op, tenant) -> outcome`` is
    injectable so tests can stand in a stalled or fake server; the
    production executor is ``RestExecutor``."""

    def __init__(self, packs: list, execute: Callable, *,
                 seed: int = 42, duration_s: float = 3.0,
                 max_workers: int = 48, retry_limit: int = 2,
                 retry_jitter_s: float = 0.25,
                 retry_wait_cap_s: Optional[float] = None):
        self.packs = list(packs)
        self.execute = execute
        self.seed = int(seed)
        self.duration_s = float(duration_s)
        self.max_workers = int(max_workers)
        self.retry_limit = int(retry_limit)
        self.retry_jitter_s = float(retry_jitter_s)
        #: None = honor the server's Retry-After in full; a cap is for
        #: tests that must stay fast (capping below the hint is a
        #: deliberate compliance violation the ledger still records)
        self.retry_wait_cap_s = retry_wait_cap_s

    # -- pure schedule (the determinism contract) --------------------------

    def pack_rates(self, offered_qps: float) -> dict:
        total = sum(p.weight for p in self.packs) or 1.0
        return {p.name: offered_qps * p.weight / total
                for p in self.packs}

    def schedule(self, offered_qps: float) -> list:
        """Merged (offset_s, pack_name, request_index) events, sorted —
        a pure function of (packs, seed, offered_qps, duration)."""
        rates = self.pack_rates(offered_qps)
        events = []
        for p in self.packs:
            ts = arrival_schedule(rates[p.name], self.duration_s,
                                  p.stream_seed(self.seed), p.envelope)
            events.extend((t, p.name, i) for i, t in enumerate(ts))
        events.sort()
        return events

    # -- one offered-load point --------------------------------------------

    def run_point(self, offered_qps: float) -> dict:
        events = self.schedule(offered_qps)
        by_pack = {p.name: p for p in self.packs}
        counts: dict = {}
        for _t, name, _i in events:
            counts[name] = counts.get(name, 0) + 1
        reqs = {p.name: p.requests(self.seed, counts.get(p.name, 0))
                for p in self.packs}
        jitters = {}
        for p in self.packs:
            jrng = random.Random(p.stream_seed(self.seed) ^ 0x9E3779B9)
            jitters[p.name] = [jrng.random() * self.retry_jitter_s
                               for _ in range(counts.get(p.name, 0))]
        recs: list = []
        lock = threading.Lock()
        base = time.monotonic() + 0.02
        with ThreadPoolExecutor(
                max_workers=self.max_workers,
                thread_name_prefix="loadgen") as pool:
            futs = []
            for t, name, i in events:
                delay = base + t - time.monotonic()
                if delay > 0:
                    # open-loop pacing: the dispatcher sleeps to the
                    # NEXT scheduled arrival; total sleep is bounded by
                    # the schedule's duration
                    time.sleep(delay)              # deadline
                futs.append(pool.submit(
                    self._fire, by_pack[name], reqs[name][i], base + t,
                    jitters[name][i], base, recs, lock))
            for f in futs:
                f.result()
        elapsed = max([r["done_rel_s"] for r in recs]
                      + [self.duration_s])
        return self._summarize(offered_qps, recs, elapsed)

    def _fire(self, pack: ScenarioPack, op: dict, scheduled_abs: float,
              jitter_s: float, base: float, recs: list,
              lock: threading.Lock) -> None:
        tries = 0
        status = 0
        hints_present = hints_missing = 0
        out: dict = {}
        while True:
            out = self.execute(op, pack.tenant)
            status = int(out.get("status", 0))
            if status == 429:
                hint = out.get("retry_after")
                if hint is None:
                    hints_missing += 1
                else:
                    hints_present += 1
                if tries < self.retry_limit:
                    tries += 1
                    wait = 1.0 if hint is None else float(hint)
                    if self.retry_wait_cap_s is not None:
                        wait = min(wait, self.retry_wait_cap_s)
                    # Retry-After honored: never before the hint, plus
                    # seeded jitter so retries decorrelate; bounded by
                    # retry_limit iterations
                    time.sleep(wait + jitter_s)    # backoff
                    continue
            break
        # the coordinated-omission-free charge: completion minus the
        # SCHEDULED arrival, so dispatcher/pool/server queueing all
        # count against the request that suffered them
        latency_s = time.monotonic() - scheduled_abs
        outcome = ("rejected" if status == 429 else
                   "server_error" if 500 <= status < 599 else
                   "transport_error" if status == 599 or status <= 0
                   else "partial" if out.get("partial") else "ok")
        with lock:
            recs.append({"pack": pack.name, "latency_s": latency_s,
                         "outcome": outcome, "tries_429": tries,
                         "hints_present": hints_present,
                         "hints_missing": hints_missing,
                         "done_rel_s": time.monotonic() - base})

    def _summarize(self, offered_qps: float, recs: list,
                   elapsed_s: float) -> dict:
        rates = self.pack_rates(offered_qps)
        packs = {}
        for p in self.packs:
            mine = [r for r in recs if r["pack"] == p.name]
            n_of = {o: sum(1 for r in mine if r["outcome"] == o)
                    for o in ("ok", "partial", "rejected",
                              "server_error", "transport_error")}
            lat_ms = np.asarray(
                [r["latency_s"] for r in mine
                 if r["outcome"] in ("ok", "partial")]) * 1e3
            def pct(q):
                return (round(float(np.percentile(lat_ms, q)), 3)
                        if len(lat_ms) else 0.0)
            served = n_of["ok"] + n_of["partial"]
            packs[p.name] = {
                "tenant": p.tenant,
                "offered_qps": round(rates[p.name], 2),
                "sent": len(mine),
                **n_of,
                "retries_429": sum(r["tries_429"] for r in mine),
                "retry_after_present": sum(r["hints_present"]
                                           for r in mine),
                "retry_after_missing": sum(r["hints_missing"]
                                           for r in mine),
                "p50_ms": pct(50), "p99_ms": pct(99),
                "p999_ms": pct(99.9),
                "achieved_qps": round(served / elapsed_s, 2)
                if elapsed_s else 0.0,
            }
        return {"offered_qps": float(offered_qps),
                "duration_s": self.duration_s,
                "elapsed_s": round(elapsed_s, 3), "packs": packs}

    # -- the sweep ---------------------------------------------------------

    def sweep(self, points) -> dict:
        """Run every offered-load point (ascending) and derive the
        per-pack ``max_sustainable_qps``: the highest offered rate the
        pack served with >= 99% non-degraded outcomes AND >= 80% of the
        offered throughput actually achieved."""
        results = [self.run_point(q) for q in sorted(points)]
        per_pack = {}
        for p in self.packs:
            sustained = 0.0
            for r in results:
                pr = r["packs"][p.name]
                if not pr["sent"]:
                    continue
                served = pr["ok"] + pr["partial"]
                if (served / pr["sent"] >= 0.99
                        and pr["achieved_qps"]
                        >= 0.8 * pr["offered_qps"]):
                    sustained = max(sustained, pr["offered_qps"])
            per_pack[p.name] = {"tenant": p.tenant,
                                "max_sustainable_qps": sustained}
        return {"seed": self.seed, "points": results,
                "packs": per_pack}

    # -- verdicts + attribution cross-check --------------------------------

    def client_ledger(self, sweep_result: dict) -> dict:
        """Per-tenant client-side outcome ledger for the attribution
        cross-check (``qos.check_tenant_attribution``)."""
        led = {}
        for p in self.packs:
            ok = s429 = 0
            for r in sweep_result["points"]:
                pr = r["packs"][p.name]
                ok += pr["ok"] + pr["partial"]
                s429 += (pr["retry_after_present"]
                         + pr["retry_after_missing"])
            led[p.tenant] = {"ok": ok, "status_429": s429,
                             "searchish": p.searchish}
        return led

    def verdicts(self, sweep_result: dict,
                 attribution: Optional[dict] = None) -> list:
        """SLO-verdict list in the soak runner's shape.  The verdict
        KEY SET is a pure function of the pack set (every pack gets its
        hint/transport rows whether or not it saw a 429), so identical
        seeds pin identical keys."""
        v = []
        points = sweep_result["points"]
        lowest = points[0] if points else {"packs": {}}
        n5 = sum(pr["server_error"]
                 for pr in lowest["packs"].values())
        v.append({"slo": "server_errors_at_lowest_load", "limit": 0,
                  "observed": n5, "ok": n5 == 0})
        for p in self.packs:
            present = sum(r["packs"][p.name]["retry_after_present"]
                          for r in points)
            missing = sum(r["packs"][p.name]["retry_after_missing"]
                          for r in points)
            frac = (present / (present + missing)
                    if present + missing else 1.0)
            v.append({"slo": f"retry_after_hint.{p.name}",
                      "limit": 1.0, "observed": round(frac, 4),
                      "ok": missing == 0})
            te = sum(r["packs"][p.name]["transport_error"]
                     for r in points)
            v.append({"slo": f"transport_errors.{p.name}", "limit": 0,
                      "observed": te, "ok": te == 0})
        if attribution is not None:
            for tenant in sorted(attribution):
                probs = attribution[tenant]
                row = {"slo": f"attribution.{tenant}", "limit": 0,
                       "observed": len(probs), "ok": not probs}
                if probs:
                    row["detail"] = probs
                v.append(row)
        return v


# -- node-side attribution fetch -------------------------------------------

def rest_attribution(client) -> tuple:
    """(admission_tenants, insights_tenants) fetched over REST: the
    ``_nodes/stats`` admission-control tenants block summed across
    nodes, and the ``_insights/top_queries?by=tenant`` rollups."""
    adm: dict = {}
    stats = client.nodes.stats()
    for n in (stats.get("nodes") or {}).values():
        tenants = (((n.get("search_backpressure") or {})
                    .get("admission_control") or {})
                   .get("tenants") or {})
        for label, t in tenants.items():
            m = adm.setdefault(label, {"admitted": 0, "rejected": 0,
                                       "shed": 0})
            for k in m:
                m[k] += int(t.get(k, 0))
    top = client.insights_top_queries({"by": "tenant"})
    ins = dict(top.get("tenants") or {})
    return adm, ins


# -- end-to-end entry -------------------------------------------------------

def run_latency_under_load(data_path: str, *, seed: int = 42,
                           points=(15, 45, 120),
                           duration_s: float = 3.0, n_docs: int = 600,
                           vocab_size: int = 2000,
                           admission_max_concurrent: Optional[int] = None,
                           tenant_shares: Optional[str] = None,
                           retry_limit: int = 2,
                           retry_wait_cap_s: Optional[float] = None) -> dict:
    """Boot a real node (HTTP on an ephemeral port), seed the corpus,
    sweep the offered-load points with the default scenario packs, and
    return the curve + per-pack ``max_sustainable_qps`` + verdicts
    (including the admission/insights attribution cross-check).  The
    shared entry for bench.py's ``latency_under_load`` phase and the
    harness tests."""
    from opensearch_tpu.client import OpenSearch
    from opensearch_tpu.node import Node
    from opensearch_tpu.search.qos import check_tenant_attribution

    node = Node(data_path, port=0).start()
    try:
        admin = OpenSearch([f"http://127.0.0.1:{node.port}"])
        seed_corpus(admin, n_docs=n_docs, seed=seed,
                    vocab_size=vocab_size)
        transient: dict = {}
        if tenant_shares is not None:
            transient["search.qos.tenant_shares"] = tenant_shares
        if admission_max_concurrent is not None:
            transient["search_backpressure.max_concurrent_searches"] = \
                int(admission_max_concurrent)
        if transient:
            admin.cluster.put_settings({"transient": transient})
        packs = default_packs(vocab_size=vocab_size, n_docs=n_docs)
        runner = LoadgenRunner(
            packs, RestExecutor(f"http://127.0.0.1:{node.port}"),
            seed=seed, duration_s=duration_s, retry_limit=retry_limit,
            retry_wait_cap_s=retry_wait_cap_s)
        result = runner.sweep(points)
        adm, ins = rest_attribution(admin)
        attribution = check_tenant_attribution(
            adm, ins, runner.client_ledger(result))
        result["verdicts"] = runner.verdicts(result,
                                             attribution=attribution)
        result["slo_ok"] = all(v["ok"] for v in result["verdicts"])
        return result
    finally:
        node.stop()


# -- elasticity sweep (PR 17: autoscaling moves the curve) ------------------

def _tier_search_pack(index: str = "tier", tenant: str = "tenant-sweep",
                      vocab: int = 7) -> ScenarioPack:
    """Single seeded lexical pack over the cluster tier's corpus
    (``build``-style docs carry ``body: hello t{i % 7}``)."""
    def gen(rng: random.Random, n: int) -> list:
        return [{"op": "search", "index": index,
                 "body": {"query": {"match":
                                    {"body": f"t{rng.randrange(vocab)}"}},
                          "size": 3}}
                for _ in range(n)]
    return ScenarioPack("search", tenant, 1.0, "flat", gen)


def _fleet_executor(leader, index: str) -> Callable:
    """Execute ops against an in-process ClusterNode coordinator under
    a registered tenant task (the X-Opaque-Id threading the REST edge
    performs), mapping admission 429s to the harness outcome dict."""
    from opensearch_tpu.common import tasks as taskmod
    from opensearch_tpu.common.errors import OpenSearchTpuError

    def execute(op: dict, tenant: str) -> dict:
        task = leader.task_manager.register(
            "rest:loadgen", f"[{tenant}]",
            headers={"X-Opaque-Id": tenant})
        token = taskmod.set_current(task)
        try:
            out = leader.search(op.get("index") or index,
                                dict(op.get("body") or {}))
            shards = out.get("_shards") or {}
            return {"status": 200,
                    "partial": bool(shards.get("failed"))}
        except OpenSearchTpuError as exc:
            return {"status": int(getattr(exc, "status", 500) or 500),
                    "retry_after": getattr(exc, "retry_after_seconds",
                                           None)}
        finally:
            taskmod.reset_current(token)
            leader.task_manager.unregister(task)
    return execute


def _elastic_fleet(root: str, *, service_delay_s: float,
                   n_docs: int = 21, fault_seed: int = 7) -> dict:
    """One data/master node + one searcher over a shared remote store,
    with every searcher's shard query phase delayed by
    ``service_delay_s`` (the fault injector's adaptive-replica-
    selection scenario) so admission concurrency — not CPU — is the
    binding capacity.  Returns a ctx dict whose ``build`` closure the
    autoscaler's provision hook reuses for elastic searchers."""
    from opensearch_tpu.cluster.node import ClusterNode
    from opensearch_tpu.testing.fault_injection import FaultInjector
    from opensearch_tpu.transport.service import (LocalTransport,
                                                  TransportService)

    hub = LocalTransport.Hub()
    remote = os.path.join(root, "remote")

    def build(nid: str, roles: tuple):
        svc = TransportService(nid, LocalTransport(hub))
        node = ClusterNode(nid, os.path.join(root, nid), svc, ["n0"],
                           roles=roles, remote_store_path=remote)
        # scheduled delays only: a loaded CI host's real CPU probe must
        # not leak nondeterminism into the capacity model
        node.search_backpressure.trackers["cpu_usage"].probe = \
            lambda: 0.0
        node.search_rpc_timeout = 2.0
        node.recovery_timeout = 5.0
        return node

    nodes = {"n0": build("n0", ("master", "data")),
             "s0": build("s0", ("search",))}
    leader = nodes["n0"]
    if not leader.start_election():
        raise RuntimeError("loadgen fleet: election failed")
    leader.coordinator.add_node("s0", {"name": "s0",
                                       "roles": ["search"],
                                       "master_eligible": False})
    leader.create_index("tier", {
        "settings": {"number_of_shards": 1, "number_of_replicas": 0,
                     "number_of_search_replicas": 1},
        "mappings": {"properties": {"body": {"type": "text"}}}})

    def ready() -> bool:
        routing = leader.coordinator.state().routing.get("tier", [])
        return bool(routing) and all(
            len(e.get("search_replicas") or []) >= 1
            and set(e.get("search_replicas") or [])
            == set(e.get("search_in_sync") or []) for e in routing)

    deadline = time.monotonic() + 10.0
    while not ready():                       # deadline
        if time.monotonic() > deadline:
            raise RuntimeError("loadgen fleet: searcher never ready")
        time.sleep(0.02)                     # deadline
    for i in range(n_docs):
        leader.index_doc("tier", str(i), {"body": f"hello t{i % 7}"})
    leader.refresh("tier")
    deadline = time.monotonic() + 10.0
    while nodes["s0"].search_lag() != 0:     # deadline
        if time.monotonic() > deadline:
            raise RuntimeError("loadgen fleet: searcher catch-up")
        time.sleep(0.02)                     # deadline
    faults = FaultInjector(hub, seed=fault_seed)
    faults.slow_search_node("s0", service_delay_s)
    return {"hub": hub, "nodes": nodes, "leader": leader,
            "faults": faults, "build": build}


def run_autoscale_sweep(data_path: str, *, seed: int = 42,
                        points=(8, 40, 70, 100),
                        duration_s: float = 1.5,
                        per_searcher: int = 8,
                        max_searchers: int = 3,
                        service_delay_s: float = 0.1) -> dict:
    """The elasticity curve (ROADMAP item 5): run the SAME offered-load
    ramp twice — searcher fleet pinned at min vs the QoS-driven
    autoscaler closing the loop — and compare ``max_sustainable_qps``.

    The capacity model: every search holds a coordinator admission
    permit for ~``service_delay_s`` (the injected searcher delay), so
    sustainable throughput is ``max_concurrent / service_delay_s`` and
    the autoscaler's ``concurrency_per_searcher`` link converts fleet
    size into admission capacity.  Pinned, the ramp's upper points
    saturate the permit pool and reject; autoscaled, admission
    occupancy goes hot past the dwell window mid-ramp, the fleet grows
    toward ``max_searchers``, and the later points clear.  429s are
    terminal here (``retry_limit=0``) so saturation shows up as
    rejected outcomes, not retry-shifted latency."""
    results: dict = {}
    for mode in ("pinned", "autoscaled"):
        ctx = _elastic_fleet(os.path.join(data_path, mode),
                             service_delay_s=service_delay_s)
        leader, nodes, faults = (ctx["leader"], ctx["nodes"],
                                 ctx["faults"])
        asc = leader.autoscaler
        adm = leader.search_backpressure.admission
        adm.max_concurrent = per_searcher
        if mode == "autoscaled":
            asc.enabled = True
            asc.min_searchers = 1
            asc.max_searchers = max_searchers
            asc.dwell_s = 0.15
            asc.cooldown_s = 0.4
            asc.drain_timeout_s = 2.0
            asc.interval_s = 0.04
            # occupancy rides a fast instantaneous signal here; a low
            # hot threshold keeps the dwell streak robust to sampling
            asc.hot_occupancy = 0.3
            asc.cold_occupancy = 0.0
            asc.concurrency_per_searcher = per_searcher

            def provision(nid: str, _ctx=ctx) -> dict:
                node = _ctx["build"](nid, ("search",))
                _ctx["nodes"][nid] = node
                _ctx["faults"].slow_search_node(nid, service_delay_s)
                return {"name": nid, "roles": ["search"],
                        "master_eligible": False}
            asc.provision = provision
            asc.resolve = nodes.get
            asc.on_retired = lambda nid: nodes.pop(nid, None)
        else:
            asc.enabled = False
        try:
            runner = LoadgenRunner(
                [_tier_search_pack()], _fleet_executor(leader, "tier"),
                seed=seed, duration_s=duration_s, retry_limit=0)
            res = runner.sweep(points)
            res["autoscale"] = asc.stats()
            res["audit"] = [r for r in leader.qos.audit(50)
                            if str(r.get("knob", ""))
                            .startswith("autoscale.")]
            results[mode] = res
        finally:
            for n in list(nodes.values()):
                n.stop()
    pinned_max = results["pinned"]["packs"]["search"][
        "max_sustainable_qps"]
    auto_max = results["autoscaled"]["packs"]["search"][
        "max_sustainable_qps"]
    ups = results["autoscaled"]["autoscale"]["scale_ups"]
    audited = len(results["autoscaled"]["audit"])
    verdicts = [
        {"slo": "autoscale_raises_max_sustainable_qps",
         "limit": pinned_max, "observed": auto_max,
         "ok": auto_max > pinned_max},
        {"slo": "autoscale_scale_up_fired", "limit": 1,
         "observed": ups, "ok": ups >= 1},
        {"slo": "autoscale_decisions_audited", "limit": 1,
         "observed": audited, "ok": audited >= 1},
    ]
    return {"seed": seed, "points": list(points),
            "duration_s": duration_s,
            "pinned": results["pinned"],
            "autoscaled": results["autoscaled"],
            "max_sustainable_qps": {"pinned": pinned_max,
                                    "autoscaled": auto_max},
            "verdicts": verdicts,
            "slo_ok": all(v["ok"] for v in verdicts)}
