"""Chaos-soak harness: seeded mixed workload + fault schedule + SLOs.

Analog of the reference's nightly benchmark/disruption runs (the
OpenSearch-benchmark mixed workloads driven against a cluster that
`NetworkDisruption`-style tests are killing underneath) collapsed into
one deterministic in-process subsystem:

- ``MixedWorkload``: a seeded generator of interleaved operation
  classes — zipf BM25 queries (the same query-log shape ``bench.py``
  measures), bulk ingest + refresh, ``date_histogram``/``terms``
  aggregations, scroll-style paged walks, and msearch batches.
- ``FaultSchedule``: a seeded schedule of fault directives pinned to
  operation indices (never wall clock): kill-the-leader + re-election,
  ``slow_search_node``, drop/stall rules, induced duress, and a
  symmetric network ``partition()`` — all via
  ``testing/fault_injection.py`` over the LocalTransport hub.
- ``SoakRunner``: drives a multi-node ``ClusterNode`` cluster through
  the workload while executing the schedule, collects per-op-class
  latency histograms plus rejection/shed/partial/retry accounting from
  the PR-1 metrics registry, and evaluates declarative SLOs: p99 per op
  class, a client-visible-error budget (429s and partial results are
  allowed degradation; unexpected 5xx budget is zero), and a post-fault
  convergence invariant — after the schedule drains, doc count and a
  content checksum must match an uninjected control run.

The same seed replays the same op stream, the same fault schedule, and
the same SLO verdicts — the regression gate ROADMAP item 5 asks for,
enforced in tier-1 via ``tests/test_soak.py`` and recorded as a
``soak`` phase line in ``bench_phases.jsonl`` by ``bench.py``.
"""

from __future__ import annotations

import contextlib
import json
import os
import random
import shutil
import tempfile
import threading
import time
import zlib
from typing import Callable, Optional

import numpy as np

from opensearch_tpu.common.errors import OpenSearchTpuError
from opensearch_tpu.common.telemetry import Histogram, metrics

#: transport failures a real client retries (retryable 503 class);
#: anything else client-visible above 399 that is not a 429 counts
#: against the zero-unexpected-error budget.  ``primary_fenced`` is the
#: replication-safety 503: the write was NOT acked, the slot moved —
#: retry routes to the current primary.  It arrives as a REMOTE type
#: (status 500 on the wire), so the name must be listed here — the
#: status==503 fallback only covers locally-raised fences.
_RETRYABLE_TYPES = ("node_disconnected_exception",
                    "receive_timeout_transport_exception",
                    "no_master_exception", "coordination_exception",
                    "primary_fenced_exception")


def _bump(ctx: dict, key: str, n: int = 1) -> None:
    """Locked counter increment — the full configuration runs ops on a
    worker pool, so the run context's tallies must not race."""
    with ctx["lock"]:
        ctx[key] += n


def zipf_query_log(n_queries: int, vocab_size: int,
                   seed: int = 7, a: float = 1.3) -> list:
    """Seeded zipf query log: ``n_queries`` two-term BM25 queries over a
    ranked vocabulary — the exact sampling ``bench.py`` measures with
    (bench imports THIS function), reused here so soak traffic has the
    same term-frequency shape as the flagship benchmark."""
    rng = np.random.default_rng(seed)
    pairs = []
    for _ in range(n_queries):
        x, y = (rng.zipf(a, size=2) - 1).clip(0, vocab_size - 1)
        pairs.append((int(x), int(y)))
    return pairs


def corpus_doc(seed: int, i: int, vocab_size: int, tags: list) -> dict:
    """Deterministic per-id document: zipf text body, a timestamp
    walking forward one minute per doc (date_histogram fodder), a
    zipf-ish tag (terms-agg fodder), and a sortable long.  Module-level
    so the open-loop harness (``testing/loadgen.py``) seeds its corpus
    with the exact same doc shape the soak exercises; the RNG
    construction and draw order are part of the determinism contract —
    ``MixedWorkload.make_doc`` delegates here and tests pin its
    output."""
    rng = random.Random((seed << 20) ^ i)
    n_terms = rng.randint(4, 10)
    body = " ".join(
        f"t{min(int(rng.paretovariate(1.3)) - 1, vocab_size - 1)}"
        for _ in range(n_terms))
    return {"body": body,
            "ts": 1_700_000_000_000 + i * 60_000,
            "tag": tags[min(int(rng.paretovariate(1.5)) - 1,
                            len(tags) - 1)],
            "v": i}


class SoakConfig:
    """Declarative soak scenario: workload mix, cluster shape, fault
    schedule knobs, and SLOs.  ``smoke()`` is the fixed-seed tier-1
    configuration (small, deterministic, seconds); ``full()`` is the
    production soak marked ``slow`` in the test suite."""

    def __init__(self, *, seed: int = 42, n_ops: int = 48,
                 n_docs: int = 24, bulk_size: int = 3,
                 vocab_size: int = 48, index: str = "soak",
                 shards: int = 2, replicas: int = 1,
                 node_ids: tuple = ("n0", "n1", "n2"),
                 search_replicas: int = 0,
                 searcher_ids: tuple = (),
                 client: str = "n1", concurrency: int = 1,
                 search_rpc_timeout: float = 0.5,
                 max_retries: int = 6,
                 faults_enabled: bool = True,
                 control_run: bool = True,
                 device_faults: bool = False,
                 autoscale: bool = False,
                 schedule: Optional[list] = None,
                 slos: Optional[dict] = None):
        self.seed = int(seed)
        self.n_ops = int(n_ops)
        self.n_docs = int(n_docs)
        self.bulk_size = int(bulk_size)
        self.vocab_size = int(vocab_size)
        self.index = index
        self.shards = int(shards)
        self.replicas = int(replicas)
        self.node_ids = tuple(node_ids)
        # search-only replica tier: ``searcher_ids`` name the
        # search-role nodes (stateless over the shared remote store),
        # ``search_replicas`` the per-shard searcher slots; > 0 enables
        # the tier directive class (kill/add searcher, remote-store
        # stall)
        self.search_replicas = int(search_replicas)
        self.searcher_ids = tuple(searcher_ids)
        if self.search_replicas and not self.searcher_ids:
            raise ValueError(
                "search_replicas > 0 requires searcher_ids")
        # accelerator fault class: the pass forces device kernels on
        # (bm25_ops.HOST_SCORING=False) and the schedule gains the
        # device_oom / device_poison / device_slow / device_mesh_loss /
        # device_heal directives (testing/fault_injection.py
        # DeviceFaultInjector + common/device_health.py breakers)
        self.device_faults = bool(device_faults)
        # elasticity class: the leader gets a SearcherAutoscaler on an
        # injectable clock (advanced only by the scale_up_pressure /
        # scale_down_idle directives, so ticks are deterministic) wired
        # to provision/retire soak searcher nodes
        self.autoscale = bool(autoscale)
        self.client = client
        self.concurrency = int(concurrency)
        self.search_rpc_timeout = float(search_rpc_timeout)
        self.max_retries = int(max_retries)
        self.faults_enabled = bool(faults_enabled)
        self.control_run = bool(control_run)
        # an explicit directive list overrides the seeded generator —
        # focused scenarios (partition-only round-trips, single-fault
        # repros) reuse the whole runner
        self.schedule = schedule
        self.slos = slos if slos is not None else {
            # generous CI-safe p99 bounds: the verdicts must be
            # deterministic across runs/hosts; the OBSERVED p99 is what
            # the bench trajectory tracks run over run
            "p99_ms": {"search": 10_000.0, "msearch": 20_000.0,
                       "bulk": 10_000.0, "agg": 15_000.0,
                       "scroll": 15_000.0},
            "max_rejection_rate": 0.5,
            "max_unexpected_errors": 0,
            "require_convergence": True,
            # replication-safety SLOs (testing/history.py): the
            # post-drain durability audit must find zero lost acked
            # writes / zero stale acks, and every write copy (plus the
            # search tier) must serve an identical per-doc
            # (seq_no, primary_term, version) digest
            "no_lost_acked_writes": True,
            "no_stale_acks": True,
            "require_copy_parity": True,
        }

    @classmethod
    def smoke(cls, **overrides) -> "SoakConfig":
        return cls(**overrides)

    @classmethod
    def full(cls, **overrides) -> "SoakConfig":
        base = {"n_ops": 400, "n_docs": 400, "bulk_size": 10,
                "vocab_size": 2000, "concurrency": 4}
        base.update(overrides)
        return cls(**base)

    @classmethod
    def tier(cls, **overrides) -> "SoakConfig":
        """The search-tier scenario: 3 data nodes + 2 search-only
        replicas per shard over the shared remote store, with the
        searcher directive class (kill/add searcher mid-traffic,
        remote-store stall) in the schedule."""
        base = {"search_replicas": 2, "searcher_ids": ("s0", "s1")}
        base.update(overrides)
        return cls(**base)

    @classmethod
    def autoscale_churn(cls, **overrides) -> "SoakConfig":
        """The elasticity scenario: one seed searcher, the autoscaler
        on the leader (= the client, so admission evidence and
        actuation share a node), and an explicit schedule driving one
        hot window (held admission permits past the dwell) and one idle
        window.  SLOs require >= 1 audited scale-up and >= 1
        drain-complete retirement with the standard p99 / unexpected-
        error / convergence bounds holding across both transitions."""
        base = {"search_replicas": 1, "searcher_ids": ("s0",),
                "client": "n0", "autoscale": True, "n_ops": 32,
                "schedule": [
                    {"step": 8, "fault": "scale_up_pressure"},
                    {"step": 20, "fault": "scale_down_idle"},
                ]}
        base.update(overrides)
        cfg = cls(**base)
        cfg.slos.setdefault("require_scale_up", True)
        cfg.slos.setdefault("require_drain_complete", True)
        return cfg

    @classmethod
    def device(cls, **overrides) -> "SoakConfig":
        """The accelerator-fault scenario: device kernels forced on,
        the device fault directive class in the schedule, and the
        device SLOs — zero unexpected 5xx, convergence vs the
        uninjected control, >= 1 breaker trip visible, breakers
        re-closed after heal (mesh exempt: on a 1-device CPU host the
        mesh stays legitimately demoted), and >= 1 poisoned result
        caught by the sanity guard."""
        base = {"device_faults": True}
        base.update(overrides)
        cfg = cls(**base)
        cfg.slos.setdefault("require_breaker_trip", True)
        cfg.slos.setdefault("require_breaker_reclose", True)
        cfg.slos.setdefault("require_poison_detected", True)
        return cfg


class MixedWorkload:
    """Seeded mixed-operation stream.  Every op is a plain dict (class +
    parameters), so the stream is inspectable, replayable, and identical
    across runs with the same config."""

    CLASSES = ("search", "msearch", "bulk", "agg", "scroll")

    def __init__(self, config: SoakConfig):
        self.config = config
        self._rng = random.Random(config.seed)
        self._doc_seq = config.n_docs          # ids after the seed corpus
        self._queries = zipf_query_log(
            max(64, config.n_ops * 2), config.vocab_size,
            seed=config.seed)
        self._qi = 0
        self.tags = [f"tag{i}" for i in range(8)]

    # -- documents ---------------------------------------------------------

    def make_doc(self, i: int) -> dict:
        """Deterministic per-id document — delegates to the shared
        ``corpus_doc`` so soak and loadgen corpora stay byte-identical
        for the same seed."""
        return corpus_doc(self.config.seed, i, self.config.vocab_size,
                          self.tags)

    def seed_docs(self) -> list:
        return [(str(i), self.make_doc(i)) for i in range(self.config.n_docs)]

    # -- operations --------------------------------------------------------

    def _next_query(self) -> dict:
        a, b = self._queries[self._qi % len(self._queries)]
        self._qi += 1
        return {"query": {"match": {"body": f"t{a} t{b}"}}, "size": 10}

    def _op(self, kind: str) -> dict:
        if kind == "search":
            return {"op": "search", "body": self._next_query()}
        if kind == "msearch":
            return {"op": "msearch",
                    "bodies": [self._next_query() for _ in range(4)]}
        if kind == "bulk":
            docs = []
            for _ in range(self.config.bulk_size):
                i = self._doc_seq
                self._doc_seq += 1
                docs.append((str(i), self.make_doc(i)))
            delete_id = None
            if self._rng.random() < 0.2 and self._doc_seq > 4:
                # delete an early seed doc (deterministic victim), the
                # mixed-workload CRUD shape; convergence tracks it too
                delete_id = str(self._rng.randrange(4))
            return {"op": "bulk", "docs": docs, "delete": delete_id,
                    "refresh": self._rng.random() < 0.5}
        if kind == "agg":
            if self._rng.random() < 0.5:
                aggs = {"per_hour": {"date_histogram": {
                    "field": "ts", "fixed_interval": "1h"}}}
            else:
                aggs = {"tags": {"terms": {"field": "tag", "size": 8}}}
            return {"op": "agg",
                    "body": {"query": {"match_all": {}}, "size": 0,
                             "aggs": aggs}}
        if kind == "scroll":
            return {"op": "scroll", "page_size": 8, "max_pages": 3}
        raise ValueError(kind)

    def ops(self) -> list:
        """The full seeded op stream: weighted mix, search-heavy like
        the reference's default benchmark workloads."""
        weights = {"search": 0.40, "msearch": 0.15, "bulk": 0.20,
                   "agg": 0.15, "scroll": 0.10}
        kinds = list(weights)
        cum = np.cumsum([weights[k] for k in kinds])
        out = []
        for _ in range(self.config.n_ops):
            r = self._rng.random()
            kind = kinds[int(np.searchsorted(cum, r))]
            out.append(self._op(kind))
        return out


class FaultSchedule:
    """Seeded fault directives pinned to op indices.  A directive is a
    dict ``{"step": i, "fault": name, ...params}``; the runner applies
    every directive whose step equals the index of the op about to
    execute, so the interleaving is a pure function of the seed."""

    @staticmethod
    def generate(config: SoakConfig) -> list:
        rng = random.Random(config.seed ^ 0x5EED)
        n = config.n_ops
        client = config.client
        others = [nid for nid in config.node_ids if nid != client]
        slow_victim = rng.choice(others)
        drop_victim = rng.choice(others)
        stall_victim = rng.choice(others)
        # duress on the two non-client nodes: every shard with both
        # copies there becomes sheddable once the coordinator learns
        duress_victims = others[:2]
        # partition isolates a non-client follower; the kill targets the
        # elected leader (re-election is the point)
        part_victim = next(nid for nid in others if nid != "n0") \
            if "n0" in others else rng.choice(others)
        # seeded jitter on each slot (clamped monotone so paired
        # directives — stall/release, induce/clear, unhealthy/heal,
        # partition/heal, kill/restart — keep their order): where a
        # fault lands in the op stream is part of the schedule the seed
        # replays.  Disk faults (corrupt_segment, disk_unhealthy) ride
        # the same schedule — the fault class PRs 2-7 couldn't inject.
        jitter = max(1, n // 24)
        at: list = []
        for f in (0.08, 0.16, 0.24, 0.32, 0.38, 0.46, 0.54,
                  0.60, 0.68, 0.76, 0.84, 0.90, 0.96):
            base = max(1, int(n * f)) + rng.randint(0, jitter)
            at.append(min(max(at[-1] if at else 1, base), n - 1))
        out = [
            {"step": at[0], "fault": "slow_node", "node": slow_victim,
             "seconds": 0.05, "times": 2},
            {"step": at[1], "fault": "drop_write", "node": drop_victim,
             "times": 1},
            {"step": at[2], "fault": "stall_search", "node": stall_victim,
             "times": 2},
            {"step": at[3], "fault": "release_stall"},
            # disk fault 1: a seeded bit-flip in one replica's committed
            # segment file — detection, A_FAIL_COPY, drop + re-recovery
            {"step": at[4], "fault": "corrupt_segment"},
            {"step": at[5], "fault": "induce_duress",
             "nodes": list(duress_victims)},
            {"step": at[6], "fault": "clear_duress",
             "nodes": list(duress_victims)},
            # disk fault 2: a node whose fsync probe starts failing is
            # evicted by the leader (FsHealth piggyback), then healed
            {"step": at[7], "fault": "disk_unhealthy"},
            {"step": at[8], "fault": "disk_heal"},
            {"step": at[9], "fault": "partition", "node": part_victim},
            {"step": at[10], "fault": "heal_partition",
             "node": part_victim},
            {"step": at[11], "fault": "kill_leader"},
            {"step": at[12], "fault": "restart_killed"},
        ]
        if config.search_replicas and config.searcher_ids:
            # searcher-tier directive class: remote-store outage
            # (stall + release), then kill a searcher mid-traffic and
            # add a fresh one — SLOs must hold and doc-count+checksum
            # convergence must survive the fleet rebalancing.  Seeded
            # like the base schedule: paired directives stay ordered
            # under the jitter.
            s_at: list = []
            for f in (0.20, 0.30, 0.44, 0.58):
                base = max(1, int(n * f)) + rng.randint(0, jitter)
                s_at.append(min(max(s_at[-1] if s_at else 1, base),
                                n - 1))
            victim = config.searcher_ids[0]
            out += [
                {"step": s_at[0], "fault": "stall_remote_store"},
                {"step": s_at[1], "fault": "release_remote_store"},
                {"step": s_at[2], "fault": "kill_searcher",
                 "node": victim},
                {"step": s_at[3], "fault": "add_searcher",
                 "node": f"{victim}r"},
            ]
        if config.device_faults:
            # accelerator fault class (the single fault domain the
            # cluster directives above never touch): slow device, then
            # NaN-poisoned top-k (sanity guard + dispatch breaker),
            # heal, then sticky staging OOM over force-evicted
            # segments (restage failures + host fallbacks), mesh
            # member loss probes, final heal with breaker-re-close
            # probes.  Seeded like the rest: paired windows stay
            # ordered under the jitter.
            d_at: list = []
            for f in (0.10, 0.22, 0.34, 0.48, 0.62, 0.76):
                base = max(1, int(n * f)) + rng.randint(0, jitter)
                d_at.append(min(max(d_at[-1] if d_at else 1, base),
                                n - 1))
            out += [
                {"step": d_at[0], "fault": "device_slow",
                 "seconds": 0.02, "times": 3},
                {"step": d_at[1], "fault": "device_poison", "times": 3},
                {"step": d_at[2], "fault": "device_heal"},
                {"step": d_at[3], "fault": "device_oom"},
                {"step": d_at[4], "fault": "device_mesh_loss",
                 "probes": 3},
                {"step": d_at[5], "fault": "device_heal"},
            ]
        # split-brain manufacture (self-contained: partition -> writes
        # -> election -> heal -> fenced writes -> readmit, all inside
        # one directive) runs LAST, after the cluster is whole again —
        # and its rng draw comes after every other directive class's
        # draws, so every pre-existing schedule stays byte-identical
        sb = min(max(at[-1],
                     max(1, int(n * 0.98)) + rng.randint(0, jitter)),
                 n - 1)
        out.append({"step": sb, "fault": "isolate_primary_with_writes",
                    "writes": 2})
        return out


class SoakRunner:
    """Drives the cluster through the workload + schedule, twice when a
    control run is requested: once uninjected (the convergence
    reference) and once under chaos.  ``run()`` returns the full report
    — SLO verdicts included, breaches REPORTED, never swallowed."""

    def __init__(self, data_path: Optional[str] = None,
                 config: Optional[SoakConfig] = None):
        self.config = config or SoakConfig.smoke()
        self._own_dir = data_path is None
        self.data_path = data_path or tempfile.mkdtemp(prefix="soak-")

    # -- cluster plumbing --------------------------------------------------

    def _wait(self, pred: Callable[[], bool], timeout: float = 20.0,
              what: str = "condition") -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:   # deadline
            if pred():
                return
            time.sleep(0.02)                 # deadline
        raise SoakHarnessError(f"soak harness: timed out waiting for {what}")

    def _build_node(self, hub, nid: str, root: str,
                    roles: tuple = ("master", "data")):
        from opensearch_tpu.cluster.node import ClusterNode
        from opensearch_tpu.transport.service import (LocalTransport,
                                                      TransportService)
        svc = TransportService(nid, LocalTransport(hub))
        # with a search tier configured, every node points at the same
        # shared blob store (primaries upload, searchers refill)
        remote = (f"{root}/remote" if self.config.search_replicas
                  else None)
        node = ClusterNode(nid, f"{root}/{nid}", svc,
                           list(self.config.node_ids), roles=roles,
                           remote_store_path=remote)
        # neutralize the real CPU probe: only SCHEDULED duress may fire
        # (a loaded CI host must not leak nondeterminism into verdicts)
        node.search_backpressure.trackers["cpu_usage"].probe = lambda: 0.0
        node.search_rpc_timeout = self.config.search_rpc_timeout
        node.recovery_timeout = max(5.0, self.config.search_rpc_timeout)
        return node

    def _searcher_info(self, nid: str) -> dict:
        return {"name": nid, "roles": ["search"],
                "master_eligible": False}

    def _searchers_ready(self, ctx: dict) -> bool:
        """Every shard's search slots are filled by live searcher nodes
        and every filled slot has reported its remote refill done."""
        nodes = ctx["nodes"]
        state = nodes[ctx["leader"]].coordinator.state()
        routing = state.routing.get(self.config.index, [])
        alive = [nid for nid in ctx["searchers"] if nid in nodes
                 and nid in state.nodes]
        want = min(self.config.search_replicas, len(alive))
        return bool(routing) and all(
            len(e.get("search_replicas") or []) >= want
            and set(e.get("search_replicas") or [])
            == set(e.get("search_in_sync") or []) for e in routing)

    def _searchers_caught_up(self, ctx: dict) -> bool:
        """Post-drain: every ready searcher copy has installed a
        checkpoint at (or past) its primary's current seq — the
        precondition for doc-count/checksum parity with the write
        tier."""
        nodes = ctx["nodes"]
        state = nodes[ctx["leader"]].coordinator.state()
        for s, e in enumerate(state.routing.get(self.config.index, [])):
            primary = e.get("primary")
            if primary not in nodes:
                return False
            engine = nodes[primary].indices[
                self.config.index].engine_for(s)
            for r in e.get("search_replicas") or []:
                if r not in nodes:
                    return False
                if nodes[r].search_installed_seq(
                        self.config.index, s) < engine._seq_no:
                    return False
        return True

    def _in_sync_full(self, nodes, leader: str) -> bool:
        state = nodes[leader].coordinator.state()
        routing = state.routing.get(self.config.index, [])
        want_repl = min(self.config.replicas, len(state.nodes) - 1)
        return bool(routing) and all(
            e.get("primary")
            and set(e["in_sync"]) == {e["primary"], *e["replicas"]}
            and len(e["replicas"]) >= want_repl for e in routing)

    # -- fault directives --------------------------------------------------

    def _apply_fault(self, d: dict, ctx: dict) -> None:
        from opensearch_tpu.cluster.node import A_SEARCH_SHARDS, A_WRITE_SHARD
        faults = ctx["faults"]
        nodes = ctx["nodes"]
        fault = d["fault"]
        ctx["applied"].append(dict(d))
        if fault == "slow_node":
            faults.slow_search_node(d["node"], d["seconds"],
                                    times=d.get("times"))
        elif fault == "drop_write":
            faults.drop(A_WRITE_SHARD, target=d["node"],
                        times=d.get("times", 1))
        elif fault == "stall_search":
            ctx["stall"] = faults.stall(A_SEARCH_SHARDS, target=d["node"],
                                        times=d.get("times"))
        elif fault == "release_stall":
            rule = ctx.pop("stall", None)
            if rule is not None:
                rule.release()
                faults.remove(rule)
        elif fault == "induce_duress":
            for nid in d["nodes"]:
                bp = nodes[nid].search_backpressure
                ctx["saved_breaches"][nid] = bp.num_successive_breaches
                bp.num_successive_breaches = 1
                faults.induce_search_duress(bp, ticks=1_000_000)
                bp.run_once()
        elif fault == "clear_duress":
            client = nodes[ctx["client"]]
            for nid in d["nodes"]:
                bp = nodes[nid].search_backpressure
                bp.force_duress(0)
                bp.run_once()                 # streak resets
                bp.num_successive_breaches = \
                    ctx["saved_breaches"].pop(nid, 3)
                # deterministic flag heal on the coordinator (the
                # record_duress seam) — TTL expiry is wall-clock and the
                # shed path never re-probes a fully-shed shard
                client.response_collector.record_duress(nid, False)
            leader = ctx["leader"]
            if leader in nodes:
                nodes[leader].coordinator.run_checks_once()
            _bump(ctx, "recoveries")
        elif fault == "corrupt_segment":
            self._corrupt_segment(ctx, d)
        elif fault == "disk_unhealthy":
            from opensearch_tpu.common.fshealth import FsHealthService
            from opensearch_tpu.testing.fault_injection import \
                DiskFaultInjector
            victim = d.get("node") or next(
                nid for nid in sorted(nodes)
                if nid not in (ctx["leader"], ctx["client"]))
            disk = DiskFaultInjector(seed=self.config.seed ^ 0xD15C)
            disk.fail_fsync(os.path.join(nodes[victim].data_path,
                                         FsHealthService.PROBE_FILE))
            disk.activate()
            ctx["disk"] = disk
            ctx["disk_victim"] = victim
            ctx["applied"][-1]["node"] = victim
            nodes[victim].fs_health.check()      # probe sees the fault
            # the unhealthy verdict piggybacks on the next follower
            # checks; after the retry budget the leader evicts the node
            # and reroutes its copies (zero client-visible failures)
            self._evict(ctx, victim)
        elif fault == "disk_heal":
            disk = ctx.pop("disk", None)
            if disk is not None:
                disk.deactivate()
            victim = ctx.pop("disk_victim", None)
            if victim is not None and victim in nodes:
                nodes[victim].fs_health.check()  # healthy again
                self._readmit(ctx, victim)
        elif fault == "isolate_primary_with_writes":
            self._isolate_primary_with_writes(ctx, d)
        elif fault == "partition":
            victim = d["node"]
            sides = ([victim],
                     [n for n in nodes if n != victim])
            ctx["partition"] = faults.partition(*sides)
            self._evict(ctx, victim)
        elif fault == "heal_partition":
            rule = ctx.pop("partition", None)
            if rule is not None:
                faults.heal_partition(rule)
            self._readmit(ctx, d["node"])
        elif fault == "kill_leader":
            victim = ctx["leader"]
            ctx["killed"] = victim
            nodes[victim].stop()
            nodes.pop(victim)
            client = ctx["client"]

            # survivors must OBSERVE the leader dead (failed
            # leader-check rounds) before they grant a pre-vote, then
            # the client (never a kill victim) stands for election
            def elected() -> bool:
                for nid, node in nodes.items():
                    retries = \
                        node.coordinator.leader_checker.settings.retries
                    for _ in range(retries + 1):
                        node.coordinator.run_checks_once()
                return nodes[client].coordinator.start_election()
            self._wait(elected, what="re-election after leader kill")
            ctx["leader"] = client
            self._evict(ctx, victim)
            _bump(ctx, "recoveries")
        elif fault == "restart_killed":
            victim = ctx.pop("killed", None)
            if victim is not None:
                hub = ctx["hub"]
                node = self._build_node(hub, victim, ctx["root"])
                ctx["nodes"][victim] = node
                self._readmit(ctx, victim)
        elif fault == "kill_searcher":
            victim = d.get("node") or next(iter(sorted(
                ctx["searchers"])))
            ctx["applied"][-1]["node"] = victim
            if victim in nodes:
                # drain-safe retirement through the ONE sanctioned path
                # (cluster/autoscaler.py): the victim leaves the C3
                # candidate sets and search_in_sync BEFORE it stops, so
                # no late scatter burns a failover attempt on a dead
                # searcher
                from opensearch_tpu.cluster.autoscaler import \
                    retire_searcher
                leader = nodes[ctx["leader"]]
                res = retire_searcher(
                    leader.coordinator, victim,
                    collector=leader.response_collector,
                    node=nodes[victim],
                    drain_timeout_s=d.get("drain_timeout_s", 5.0),
                    audit=leader.qos.record_adaptation,
                    rank=leader.response_collector.rank)
                nodes.pop(victim, None)
                ctx["searchers"].discard(victim)
                ctx["applied"][-1]["drain"] = res
                self._wait(lambda: self._searchers_ready(ctx),
                           timeout=30.0,
                           what="tier rebalance after searcher "
                                "retirement")
                _bump(ctx, "recoveries")
        elif fault == "add_searcher":
            nid = d["node"]
            node = self._build_node(ctx["hub"], nid, ctx["root"],
                                    roles=("search",))
            ctx["nodes"][nid] = node
            ctx["searchers"].add(nid)
            leader = ctx["leader"]
            nodes[leader].coordinator.add_node(
                nid, self._searcher_info(nid))
            # a FRESH searcher recovers purely by cache refill from the
            # remote store — zero primary-directed RPCs (asserted by
            # the acceptance test over transport accounting)
            self._wait(lambda: self._searchers_ready(ctx),
                       timeout=30.0,
                       what=f"remote refill of fresh searcher [{nid}]")
            _bump(ctx, "recoveries")
        elif fault == "scale_up_pressure":
            self._scale_up_pressure(ctx, d)
        elif fault == "scale_down_idle":
            self._scale_down_idle(ctx, d)
        elif fault == "device_slow":
            self._devfaults(ctx).slow_device(d.get("seconds", 0.02),
                                             times=d.get("times"))
        elif fault == "device_poison":
            self._devfaults(ctx).poison_topk(times=d.get("times", 3))
        elif fault == "device_oom":
            from opensearch_tpu.common.device_ledger import device_ledger
            # sticky staging RESOURCE_EXHAUSTED over force-evicted
            # segments: every restage attempt fails, scored term-bags
            # take the byte-identical host fallback, full-scores plans
            # degrade to partial shard failures
            self._devfaults(ctx).oom()
            led = device_ledger()
            led.set_budget(1)
            led.set_budget(0)
        elif fault == "device_mesh_loss":
            from opensearch_tpu.common.telemetry import metrics as _m
            inj = self._devfaults(ctx)
            rule = inj.lose_mesh_member()
            svc = nodes[ctx["client"]].indices.get(self.config.index)
            before_fb = _m().counter("search.mesh.fallback").value
            for _ in range(int(d.get("probes", 3))):
                # drive the mesh entry directly: member loss (or a mesh
                # that cannot build on this host) must demote to the
                # counted host scatter fallback, never raise
                resp = svc._mesh_search(
                    {"query": {"match": {"body": "t0 t1"}}, "size": 5})
                if resp.get("hits") is None:
                    raise SoakHarnessError(
                        "mesh probe returned a malformed response")
            inj.remove(rule)
            ctx["applied"][-1]["mesh_fallbacks"] = int(
                _m().counter("search.mesh.fallback").value - before_fb)
        elif fault == "device_heal":
            from opensearch_tpu.common.device_health import device_health
            inj = ctx.get("devfaults")
            if inj is not None:
                inj.clear()
            # deterministic breaker-re-close probes: a sorted scan
            # restages every evicted segment on the selected copies
            # (staging + dispatch classes), then a scored term-bag runs
            # the device kernel path again
            client = nodes[ctx["client"]]
            self._write_with_retry(ctx, lambda: client.search(
                self.config.index,
                {"query": {"match_all": {}}, "size": 1,
                 "sort": [{"v": "asc"}]}))
            self._write_with_retry(ctx, lambda: client.search(
                self.config.index,
                {"query": {"match": {"body": "t0"}}, "size": 1}))
            ctx["applied"][-1]["breaker_states"] = \
                device_health().breaker_states()
            _bump(ctx, "recoveries")
        elif fault == "stall_remote_store":
            from opensearch_tpu.testing.fault_injection import \
                RemoteStoreFaultInjector
            repos = [n.remote_store for n in nodes.values()
                     if getattr(n, "is_search", False)
                     and n.remote_store is not None]
            inj = RemoteStoreFaultInjector(repos)
            inj.stall()
            ctx["remote_stall"] = inj
        elif fault == "release_remote_store":
            inj = ctx.pop("remote_stall", None)
            if inj is not None:
                inj.release()
        else:
            raise ValueError(f"unknown fault directive [{fault}]")

    def _isolate_primary_with_writes(self, ctx: dict, d: dict) -> None:
        """Split-brain manufacture, end to end inside one directive so
        the interleaving is seed-pure: fully partition one shard's
        primary, drive writes at it (indeterminate outcomes — the
        partition eats them), let the leader evict it and promote a
        replica under a bumped term, HEAL the partition, then drive
        more writes through the deposed primary's stale routing state.
        Every late replication op must be fenced by the promoted
        lineage (``stale_primary_rejections``) and the deposed primary
        must raise the retryable 503 instead of acking — those writes
        are recorded as DEFINITE failures, so if one ever becomes
        visible the durability audit turns ``no_stale_acks`` red.
        Finally the deposed node readmits: its divergent copy rolls
        back above the global checkpoint and re-recovers, leaving the
        final state byte-identical to the control run's."""
        from opensearch_tpu.indices.service import shard_id_for
        cfg = self.config
        nodes = ctx["nodes"]
        hist = ctx["history"]
        faults = ctx["faults"]
        victim = shard = None
        for attempt in range(2):
            state = nodes[ctx["leader"]].coordinator.state()
            routing = state.routing.get(cfg.index, [])
            for s, e in enumerate(routing):
                p = e.get("primary")
                if (p and p not in (ctx["leader"], ctx["client"])
                        and p in nodes and (e.get("replicas") or [])):
                    victim, shard = p, s
                    break
            if victim is not None or attempt > 0:
                break
            # the preceding failover chain tends to park every primary
            # on the survivor-of-everything (the leader/client): force
            # a PLANNED failover through the real deposed-primary path
            # — promote an eligible in-sync replica under a bumped
            # term — then rescan, so the fence is exercised on every
            # seeded schedule, not only topology-lucky ones
            moved = False
            for s, e in enumerate(routing):
                safe = [r for r in (e.get("replicas") or [])
                        if r in (e.get("in_sync") or []) and r in nodes
                        and r not in (ctx["leader"], ctx["client"])]
                if safe and e.get("primary"):
                    nodes[ctx["leader"]]._h_fail_copy({
                        "index": cfg.index, "shard": s,
                        "node": e["primary"], "deposed": True})
                    moved = True
                    break
            if not moved:
                break
            ctx["applied"][-1]["planned_failover"] = True
            self._wait(lambda: self._in_sync_full(nodes,
                                                  ctx["leader"]),
                       timeout=30.0,
                       what="planned failover before split-brain "
                            "directive")
        ctx["applied"][-1].update(node=victim, shard=shard)
        if victim is None:
            # no movable primary either; degrade to a no-op — LOUDLY
            # (the applied record says so), never to a half-run
            ctx["applied"][-1]["skipped"] = "no eligible primary"
            return
        n_shards = len(routing)

        def ids_for(prefix: str, k: int) -> list:
            out, i = [], 0
            while len(out) < k:       # deterministic: murmur3 routing
                did = f"{prefix}{i}"
                if shard_id_for(did, None, n_shards) == shard:
                    out.append(did)
                i += 1
            return out

        writes = int(d.get("writes", 2))
        rule = faults.partition(
            [victim], [n for n in nodes if n != victim])
        # phase A: writes INTO the partition — each fails fast at the
        # cut; the outcome is indeterminate from the client's side
        # (recorded UNKNOWN: absent and present are both legal ends)
        for did in ids_for(f"sb-a-{cfg.seed}-", writes):
            src = {"body": "split brain phase a", "tag": "sb",
                   "ts": 1_700_000_000_000, "v": -1, "nonce": did}
            op_id = hist.invoke("index", did, src)
            try:
                resp = nodes[ctx["client"]].index_doc(cfg.index, did,
                                                      src)
                hist.ok(op_id, resp)
            except OpenSearchTpuError as exc:
                hist.unknown(op_id, f"{type(exc).__name__}: {exc}")
        # the leader evicts the unreachable primary; a surviving
        # in-sync replica is promoted under a bumped primary term
        self._evict(ctx, victim)
        # heal: the deposed primary can reach everyone again but still
        # BELIEVES it holds the primary slot at the old term
        faults.heal_partition(rule)
        fenced = 0
        # phase B: writes through the deposed primary's stale state —
        # its replication ops carry the old term, the promoted
        # lineage's copies fence them, and the 503 (instead of an ack)
        # makes these DEFINITE failures: unique per-attempt content, so
        # any survivor is caught as a stale ack
        for did in ids_for(f"sb-b-{cfg.seed}-", writes):
            src = {"body": "split brain phase b", "tag": "sb",
                   "ts": 1_700_000_000_000, "v": -2, "nonce": did}
            op_id = hist.invoke("index", did, src)
            try:
                resp = nodes[victim].index_doc(cfg.index, did, src)
                # an ack from a deposed primary IS the bug class this
                # directive exists to catch — record it faithfully and
                # let the durability verdict go red
                hist.ok(op_id, resp)
            except OpenSearchTpuError as exc:
                # ONLY the fence (raised instead of an ack, local to
                # the deposed owner) is a definite failure; any other
                # error (disconnect, timeout) leaves the fate open
                from opensearch_tpu.common.errors import \
                    PrimaryFencedError
                if isinstance(exc, PrimaryFencedError):
                    fenced += 1
                    hist.fail(op_id,
                              f"fenced: {type(exc).__name__}: {exc}")
                else:
                    hist.unknown(op_id,
                                 f"{type(exc).__name__}: {exc}")
        ctx["applied"][-1]["fenced_writes"] = fenced
        # readmit: the deposed copy rolls back its divergence above the
        # global checkpoint and peer-recovers under the current term
        self._readmit(ctx, victim)

    def _devfaults(self, ctx: dict):
        """Lazily activate the pass's DeviceFaultInjector (seeded from
        the soak seed, so the whole fault schedule replays)."""
        from opensearch_tpu.testing.fault_injection import \
            DeviceFaultInjector
        inj = ctx.get("devfaults")
        if inj is None:
            inj = DeviceFaultInjector(
                seed=self.config.seed ^ 0xDE7).activate()
            ctx["devfaults"] = inj
        return inj

    def _corrupt_segment(self, ctx: dict, d: dict) -> None:
        """Disk-fault directive: flush one in-sync replica copy, flip a
        seeded byte in one of its committed segment files, then run
        store verification — the copy must detect the damage, fail
        itself via ``A_FAIL_COPY``, drop its local data, and re-recover
        from the primary before the workload proceeds."""
        cfg = self.config
        nodes = ctx["nodes"]
        state = nodes[ctx["leader"]].coordinator.state()
        routing = state.routing.get(cfg.index, [])
        victim = shard = None
        for nid in sorted(nodes):
            if nid == ctx["client"]:
                continue
            for s, e in enumerate(routing):
                if nid in (e.get("replicas") or []) \
                        and nid in (e.get("in_sync") or []):
                    victim, shard = nid, s
                    break
            if victim is not None:
                break
        if victim is None:
            return                        # no in-sync replica to damage
        engine = nodes[victim].indices[cfg.index].engine_for(shard)
        engine.flush()                    # put the copy's files on disk
        seg_dir = os.path.join(engine.data_path, "segments")
        targets = [f for f in sorted(os.listdir(seg_dir))
                   if f.endswith((".npz", ".src", ".json"))]
        if not targets:
            return
        rng = random.Random(cfg.seed ^ 0xB17F11)
        path = os.path.join(seg_dir, rng.choice(targets))
        with open(path, "rb") as f:
            data = bytearray(f.read())
        if not data:
            return
        data[rng.randrange(len(data))] ^= 0xFF
        with open(path, "wb") as f:
            f.write(bytes(data))
        applied = ctx["applied"][-1]
        applied["node"], applied["shard"] = victim, shard
        report = nodes[victim].verify_local_stores(cfg.index)
        applied["detected"] = any(r.get("corrupted") for r in report)
        self._wait(lambda: self._in_sync_full(nodes, ctx["leader"]),
                   timeout=30.0,
                   what=f"re-recovery after corrupting [{victim}]")
        _bump(ctx, "recoveries")

    # -- elasticity directives ---------------------------------------------

    def _wire_autoscaler(self, ctx: dict) -> None:
        """Attach the leader's autoscaler to the harness: fake clock
        (advanced only by the scale directives — ticks from the search
        path see a frozen clock and stay pure evidence updates),
        provisioner/resolver over the soak's in-process node map, and
        bounds pinned per-instance so global knobs stay untouched."""
        nodes = ctx["nodes"]
        asc = nodes[ctx["leader"]].autoscaler
        clock = {"t": 0.0}
        asc.clock = lambda: clock["t"]
        asc.enabled = True
        asc.min_searchers = max(1, len(ctx["searchers"]))
        asc.max_searchers = asc.min_searchers + 2
        asc.dwell_s = 2.0
        asc.cooldown_s = 5.0
        asc.drain_timeout_s = 5.0

        def provision(nid: str) -> dict:
            node = self._build_node(ctx["hub"], nid, ctx["root"],
                                    roles=("search",))
            nodes[nid] = node
            ctx["searchers"].add(nid)
            return self._searcher_info(nid)

        def retired(nid: str) -> None:
            nodes.pop(nid, None)
            ctx["searchers"].discard(nid)

        asc.provision = provision
        asc.resolve = nodes.get
        asc.on_retired = retired
        ctx["scale_clock"] = clock
        ctx["autoscaler"] = asc

    def _scale_up_pressure(self, ctx: dict, d: dict) -> None:
        """Hold admission permits as a hot tenant until occupancy
        evidence crosses the scale-up threshold, advance the fake clock
        past the dwell, and let the autoscaler provision + admit a
        fresh searcher — then wait for its remote refill so SLOs are
        measured THROUGH the transition."""
        import contextlib as _ctl
        nodes = ctx["nodes"]
        asc = ctx["autoscaler"]
        clock = ctx["scale_clock"]
        adm = nodes[ctx["leader"]].search_backpressure.admission
        tenant = d.get("tenant", "tenant-hot")
        permits = int(d.get("permits") or adm.max_concurrent)
        t0 = time.monotonic()
        with _ctl.ExitStack() as stack:
            for _ in range(permits):
                stack.enter_context(
                    adm.acquire("search", tenant=tenant))
            asc.run_once()                      # hot evidence observed
            clock["t"] += asc.dwell_s + 0.001   # dwell passes
            decision = asc.run_once()           # actuation
        if decision.get("action") != "scale_up":
            raise SoakHarnessError(
                f"scale_up_pressure did not scale: {decision}")
        self._wait(lambda: self._searchers_ready(ctx), timeout=30.0,
                   what="fresh autoscaled searcher refill")
        ctx["applied"][-1].update(
            node=decision.get("node"),
            searchers=sorted(ctx["searchers"]),
            time_to_scale_up_s=round(time.monotonic() - t0, 3))
        _bump(ctx, "recoveries")

    def _scale_down_idle(self, ctx: dict, d: dict) -> None:
        """Advance the fake clock past the cooldown with zero admission
        occupancy: the cold dwell (begun by the first post-scale-up
        tick from the traffic path, or by this directive's first
        evaluation) expires and the autoscaler retires the newest
        autoscaled searcher through the drain protocol."""
        asc = ctx["autoscaler"]
        clock = ctx["scale_clock"]
        t0 = time.monotonic()
        clock["t"] += asc.cooldown_s + 0.001
        decision = asc.run_once()
        if decision.get("action") not in ("scale_down", "resume_drain"):
            clock["t"] += asc.dwell_s + 0.001
            decision = asc.run_once()
        if decision.get("action") not in ("scale_down", "resume_drain"):
            raise SoakHarnessError(
                f"scale_down_idle did not drain: {decision}")
        self._wait(lambda: self._searchers_ready(ctx), timeout=30.0,
                   what="tier rebalance after autoscaled drain")
        ctx["applied"][-1].update(
            node=decision.get("node"),
            drain=decision.get("drain"),
            searchers=sorted(ctx["searchers"]),
            drain_s=round(time.monotonic() - t0, 3))
        _bump(ctx, "recoveries")

    def _evict(self, ctx: dict, victim: str) -> None:
        """Drive the leader's fault detection until the victim leaves
        the cluster state and surviving copies are promoted."""
        nodes = ctx["nodes"]
        leader = ctx["leader"]
        retries = nodes[leader].coordinator.follower_checker.settings.retries

        def gone():
            for _ in range(retries + 1):
                nodes[leader].coordinator.run_checks_once()
            return victim not in nodes[leader].coordinator.state().nodes
        self._wait(gone, what=f"eviction of [{victim}]")
        self._wait(lambda: self._in_sync_full(nodes, leader),
                   what=f"promotion after [{victim}] eviction")

    def _readmit(self, ctx: dict, victim: str) -> None:
        """Re-add an evicted/restarted node and wait for peer recovery
        to bring its copies back in sync."""
        nodes = ctx["nodes"]
        leader = ctx["leader"]
        nodes[leader].coordinator.add_node(victim, {"name": victim})
        self._wait(lambda: victim in
                   nodes[ctx["client"]].coordinator.state().nodes,
                   what=f"[{victim}] rejoining")
        self._wait(lambda: self._in_sync_full(nodes, leader),
                   timeout=30.0,
                   what=f"recovery after [{victim}] rejoined")
        _bump(ctx, "recoveries")

    # -- op execution ------------------------------------------------------

    def _execute(self, op: dict, ctx: dict) -> dict:
        client = ctx["nodes"][ctx["client"]]
        index = self.config.index
        kind = op["op"]
        if kind in ("search", "agg"):
            resp = client.search(index, dict(op["body"]))
            return {"partial": resp["_shards"]["failed"] > 0}
        if kind == "msearch":
            out = client.msearch(index,
                                 [dict(b) for b in op["bodies"]])
            partial = False
            for sub in out["responses"]:
                err = sub.get("error")
                if err is not None:
                    status = sub.get("status", 500)
                    if status == 429:
                        _bump(ctx, "rejected")
                    else:
                        raise SoakUnexpectedError(
                            f"msearch sub-request failed: {err}")
                elif sub["_shards"]["failed"] > 0:
                    partial = True
            return {"partial": partial}
        if kind == "bulk":
            for doc_id, source in op["docs"]:
                self._recorded_write(
                    ctx, "index", doc_id, source,
                    lambda d=doc_id, s=source:
                    client.index_doc(index, d, s))
            if op.get("delete"):
                self._recorded_write(
                    ctx, "delete", op["delete"], None,
                    lambda: client.delete_doc(index, op["delete"]))
            if op.get("refresh"):
                self._write_with_retry(
                    ctx, lambda: client.refresh(index))
            return {"partial": False}
        if kind == "scroll":
            from_, partial = 0, False
            for _ in range(op["max_pages"]):
                resp = client.search(index, {
                    "query": {"match_all": {}},
                    "size": op["page_size"], "from": from_,
                    "sort": [{"v": "asc"}]})
                partial = partial or resp["_shards"]["failed"] > 0
                got = len(resp["hits"]["hits"])
                from_ += got
                if got < op["page_size"]:
                    break
            return {"partial": partial}
        raise ValueError(kind)

    def _retryable(self, exc: OpenSearchTpuError) -> bool:
        from opensearch_tpu.common.errors import NodeDisconnectedError
        from opensearch_tpu.transport.service import (ReceiveTimeoutError,
                                                      RemoteTransportError)
        if isinstance(exc, (NodeDisconnectedError, ReceiveTimeoutError)):
            return True
        if isinstance(exc, RemoteTransportError):
            return exc.remote_type in _RETRYABLE_TYPES
        return getattr(exc, "error_type", "") in _RETRYABLE_TYPES \
            or getattr(exc, "status", 0) == 503

    def _write_with_retry(self, ctx: dict, fn: Callable[[], dict]):
        """Client-side bounded write retry (the reference client's
        retry-on-503): a transient transport failure retries after the
        cluster reconverges; exhaustion is an unexpected error."""
        last: Optional[BaseException] = None
        for attempt in range(self.config.max_retries + 1):
            try:
                return fn()
            except OpenSearchTpuError as exc:
                if not self._retryable(exc):
                    raise
                last = exc
                _bump(ctx, "client_retries")
                # reconvergence beat: the leader's checks evict dead
                # copies so the retry routes around them
                leader = ctx["leader"]
                if leader in ctx["nodes"]:
                    ctx["nodes"][leader].coordinator.run_checks_once()
                time.sleep(0.01 * (attempt + 1))   # backoff
        raise SoakUnexpectedError(
            f"write retries exhausted: {type(last).__name__}: {last}")

    def _recorded_write(self, ctx: dict, op: str, doc_id: str,
                        source: Optional[dict], fn: Callable[[], dict]):
        """A ``_write_with_retry`` with its interval recorded in the
        durability history: an ack is OK (with the response's
        ``(seq_no, primary_term, version)``), exhausted retries are
        UNKNOWN (an earlier attempt may have landed), and a
        first-attempt hard rejection is a definite FAIL."""
        hist = ctx["history"]
        op_id = hist.invoke(op, doc_id, source)
        attempts = {"n": 0}

        def counted():
            attempts["n"] += 1
            return fn()
        try:
            resp = self._write_with_retry(ctx, counted)
        except SoakUnexpectedError as exc:
            hist.unknown(op_id, f"retries exhausted: {exc}")
            raise
        except OpenSearchTpuError as exc:
            if attempts["n"] <= 1:
                # rejected outright — the write never applied anywhere
                hist.fail(op_id, f"{type(exc).__name__}: {exc}")
            else:
                # a retried attempt may have landed before this error
                hist.unknown(op_id, f"{type(exc).__name__}: {exc}")
            raise
        hist.ok(op_id, resp if isinstance(resp, dict) else {})
        return resp

    def _run_op(self, i: int, op: dict, ctx: dict) -> None:
        hist = ctx["hists"][op["op"]]
        t0 = time.monotonic()
        try:
            out = self._execute(op, ctx)
            if out.get("partial"):
                _bump(ctx, "partial_results")
        except SoakUnexpectedError as exc:
            ctx["unexpected"].append(f"op {i} [{op['op']}]: {exc}")
        except OpenSearchTpuError as exc:
            if getattr(exc, "status", 0) == 429:
                _bump(ctx, "rejected")
            elif self._retryable(exc) and op["op"] != "bulk":
                # reads fail over internally; a residual transport error
                # after failover is retried ONCE like a real client...
                try:
                    _bump(ctx, "client_retries")
                    out = self._execute(op, ctx)
                    if out.get("partial"):
                        _bump(ctx, "partial_results")
                except OpenSearchTpuError as exc2:
                    ctx["unexpected"].append(
                        f"op {i} [{op['op']}]: "
                        f"{type(exc2).__name__}: {exc2}")
            else:
                ctx["unexpected"].append(
                    f"op {i} [{op['op']}]: {type(exc).__name__}: {exc}")
        finally:
            hist.observe((time.monotonic() - t0) * 1000.0)

    # -- one full pass -----------------------------------------------------

    def _counter_snapshot(self) -> dict:
        return dict(metrics().stats()["counters"])

    def _run_once(self, label: str, inject: bool) -> dict:
        from opensearch_tpu.testing.fault_injection import FaultInjector
        from opensearch_tpu.transport.service import LocalTransport

        cfg = self.config
        root = f"{self.data_path}/{label}"
        hub = LocalTransport.Hub()
        nodes = {nid: self._build_node(hub, nid, root)
                 for nid in cfg.node_ids}
        for sid in cfg.searcher_ids:
            nodes[sid] = self._build_node(hub, sid, root,
                                          roles=("search",))
        from opensearch_tpu.testing.history import HistoryRecorder
        ctx = {
            "lock": threading.Lock(),
            "hub": hub, "nodes": nodes, "root": root,
            "client": cfg.client, "leader": cfg.node_ids[0],
            "searchers": set(cfg.searcher_ids),
            "faults": FaultInjector(hub, seed=cfg.seed),
            # acked-write durability audit (testing/history.py): every
            # CRUD write records an invoke/ok|fail|unknown interval;
            # the post-drain DurabilityChecker replays it against the
            # final state + per-copy digests (both passes record, so
            # the checker is validated on the happy path too)
            "history": HistoryRecorder(),
            "applied": [], "saved_breaches": {},
            "rejected": 0, "partial_results": 0, "client_retries": 0,
            "recoveries": 0, "unexpected": [],
            "hists": {k: Histogram(f"soak.{k}")
                      for k in ("search", "msearch", "bulk", "agg",
                                "scroll")},
        }
        host_scoring_saved = None
        dh_saved = None
        if cfg.device_faults:
            # both passes run the DEVICE kernels (control included, so
            # convergence compares like with like) on a freshly-reset
            # health service with a snappy breaker: threshold 2, zero
            # cooldown (open -> half-open probe on the next request —
            # wall-clock-free, so verdicts stay deterministic)
            from opensearch_tpu.common.device_health import device_health
            from opensearch_tpu.ops import bm25 as bm25_ops
            dh = device_health()
            dh_saved = (dh.enabled, dh.failure_threshold,
                        dh.open_interval_s)
            dh.reset()
            dh.set_failure_threshold(2)
            dh.set_open_interval_s(0.0)
            host_scoring_saved = bm25_ops.HOST_SCORING
            bm25_ops.HOST_SCORING = False
        before = self._counter_snapshot()
        workload = MixedWorkload(cfg)
        schedule = ((cfg.schedule if cfg.schedule is not None
                     else FaultSchedule.generate(cfg))
                    if inject else [])
        by_step: dict[int, list] = {}
        for d in schedule:
            by_step.setdefault(d["step"], []).append(d)
        try:
            if not nodes[ctx["leader"]].start_election():
                raise SoakHarnessError("initial election failed")
            self._wait(lambda: all(
                nodes[i].coordinator.state().master_node == ctx["leader"]
                for i in nodes if i not in ctx["searchers"]),
                what="initial leader convergence")
            for sid in sorted(ctx["searchers"]):
                nodes[ctx["leader"]].coordinator.add_node(
                    sid, self._searcher_info(sid))
            settings = {"number_of_shards": cfg.shards,
                        "number_of_replicas": cfg.replicas}
            if cfg.search_replicas:
                settings["number_of_search_replicas"] = \
                    cfg.search_replicas
            nodes[ctx["client"]].create_index(cfg.index, {
                "settings": settings,
                "mappings": {"properties": {
                    "body": {"type": "text"},
                    "ts": {"type": "date"},
                    "tag": {"type": "keyword"},
                    "v": {"type": "long"}}}})
            self._wait(lambda: self._in_sync_full(nodes, ctx["leader"]),
                       what="initial shard allocation")
            if ctx["searchers"]:
                self._wait(lambda: self._searchers_ready(ctx),
                           what="initial searcher refill")
            if cfg.autoscale:
                self._wire_autoscaler(ctx)
            for doc_id, source in workload.seed_docs():
                self._recorded_write(
                    ctx, "index", doc_id, source,
                    lambda d=doc_id, s=source:
                    nodes[ctx["client"]].index_doc(cfg.index, d, s))
            nodes[ctx["client"]].refresh(cfg.index)

            ops = workload.ops()
            if cfg.concurrency <= 1:
                for i, op in enumerate(ops):
                    for d in by_step.get(i, []):
                        self._apply_fault(d, ctx)
                    self._run_op(i, op, ctx)
            else:
                self._run_concurrent(ops, by_step, ctx)

            # drain: lift every remaining fault, restart anything still
            # dead, and wait for full in-sync recovery before measuring
            stall = ctx.pop("stall", None)
            if stall is not None:
                stall.release()
            remote_stall = ctx.pop("remote_stall", None)
            if remote_stall is not None:
                remote_stall.release()
            devfaults = ctx.get("devfaults")
            if devfaults is not None:
                devfaults.clear()       # schedule should have healed;
                #                         the drain lifts stragglers
            ctx["faults"].clear()
            disk = ctx.pop("disk", None)
            if disk is not None:
                disk.deactivate()
            disk_victim = ctx.pop("disk_victim", None)
            if disk_victim is not None and disk_victim in nodes:
                nodes[disk_victim].fs_health.check()
                if disk_victim not in \
                        nodes[ctx["leader"]].coordinator.state().nodes:
                    self._readmit(ctx, disk_victim)
            for nid, bp_breaches in list(ctx["saved_breaches"].items()):
                bp = nodes[nid].search_backpressure
                bp.force_duress(0)
                bp.run_once()
                bp.num_successive_breaches = bp_breaches
                del ctx["saved_breaches"][nid]
            if ctx.get("killed"):
                self._apply_fault({"fault": "restart_killed", "step": -1},
                                  ctx)
            self._wait(lambda: self._in_sync_full(nodes, ctx["leader"]),
                       timeout=30.0, what="post-drain recovery")
            self._write_with_retry(
                ctx, lambda: nodes[ctx["client"]].refresh(cfg.index))
            if ctx["searchers"]:
                # convergence must hold on the SEARCH tier too: every
                # ready searcher installs the final checkpoint before
                # the doc-count/checksum read (re-refreshing re-fires
                # the publish for any copy that missed one mid-churn)
                def tier_converged() -> bool:
                    if not self._searchers_ready(ctx):
                        return False
                    if self._searchers_caught_up(ctx):
                        return True
                    self._write_with_retry(
                        ctx, lambda: nodes[ctx["client"]].refresh(
                            cfg.index))
                    return self._searchers_caught_up(ctx)
                self._wait(tier_converged, timeout=30.0,
                           what="searcher-tier catch-up")
            final = self._final_state(ctx)
            # replication-safety audit, while the cluster is alive:
            # per-copy digest parity, then the acked-write history
            # replayed against the final state + those digests
            parity = self._copy_parity(ctx)
            durability = self._durability_report(
                ctx, parity.pop("copy_digests"))
            device_report = None
            if cfg.device_faults:
                # the breaker-state snapshot AFTER the drain + final
                # convergence search: the re-close SLO reads it (mesh
                # exempt — a 1-device CPU host can never rebuild the
                # mesh, so its breaker legitimately stays open)
                from opensearch_tpu.common.device_health import \
                    device_health
                dh = device_health()
                device_report = {
                    "breaker_states": dh.breaker_states(),
                    "tripped": dh.tripped_kinds(),
                    "poisoned_results": dh.stats()["poisoned_results"],
                }
            # snapshot the client/coordinator node's query-insights
            # section while the cluster is still alive: an SLO breach
            # capture below ships WITH the workload evidence (which
            # query shapes were hot when the SLO went red)
            query_insights = {
                "top_queries": nodes[ctx["client"]].insights.top(
                    by="latency", n=5),
                "coalescability":
                    nodes[ctx["client"]].insights.coalescability(),
                "totals": nodes[ctx["client"]].insights.stats(),
            }
            autoscale_report = None
            if cfg.autoscale and ctx.get("autoscaler") is not None:
                asc = ctx["autoscaler"]
                audit = (nodes[ctx["leader"]].qos.audit(64)
                         if ctx["leader"] in nodes else [])
                scale_audit = [
                    r for r in audit
                    if str(r.get("knob", "")).startswith("autoscale.")]
                autoscale_report = {
                    "scale_ups": asc.scale_ups,
                    "scale_downs": asc.scale_downs,
                    "hard_kills": asc.hard_kills,
                    "abandoned": asc.abandoned,
                    "drains_completed":
                        asc.scale_downs - asc.hard_kills,
                    "decisions_audited": len(scale_audit),
                    "audit": scale_audit[:8],
                    "searchers_final": sorted(ctx["searchers"]),
                }
        finally:
            disk = ctx.pop("disk", None)
            if disk is not None:     # exception path: unpatch open/fsync
                disk.deactivate()
            remote_stall = ctx.pop("remote_stall", None)
            if remote_stall is not None:   # exception path: unpatch reads
                remote_stall.release()
            devfaults = ctx.pop("devfaults", None)
            if devfaults is not None:   # unpatch the device entry points
                devfaults.deactivate()
            if cfg.device_faults:
                from opensearch_tpu.common.device_health import \
                    device_health
                from opensearch_tpu.ops import bm25 as bm25_ops
                bm25_ops.HOST_SCORING = host_scoring_saved
                dh = device_health()
                dh.reset()
                if dh_saved is not None:
                    dh.enabled, dh.failure_threshold, \
                        dh.open_interval_s = dh_saved
            for n in list(nodes.values()):
                n.stop()
        after = self._counter_snapshot()

        def delta(name: str) -> int:
            return after.get(name, 0) - before.get(name, 0)
        return {
            "label": label,
            "schedule": [dict(d) for d in schedule],
            "applied": ctx["applied"],
            "ops": len(ops),
            "latency_ms": {k: h.stats()
                           for k, h in ctx["hists"].items()},
            "p99_ms": {k: round(h.percentile(99), 3)
                       for k, h in ctx["hists"].items()},
            "rejected": ctx["rejected"],
            "partial_results": ctx["partial_results"],
            "client_retries": ctx["client_retries"],
            "recoveries": ctx["recoveries"],
            "unexpected_errors": list(ctx["unexpected"]),
            "sheds": delta("search.replica_selection.sheds"),
            "reroutes": delta("search.replica_selection.reroutes"),
            "failovers": delta("search.shard_failover"),
            # search-tier accounting (zeros when no tier configured)
            "searcher_refills": delta("segrep.refills"),
            "searcher_installs": delta("segrep.installs"),
            "remote_bytes_pulled": delta("segrep.bytes_pulled"),
            "internal_retries": sum(
                after.get(k, 0) - before.get(k, 0)
                for k in after if k.startswith("retry.")
                and k.endswith(".retries")),
            # replication-safety accounting: fence activity on both
            # sides (the deposed primary's refused acks, the replicas'
            # stale-op rejections), rollbacks/resyncs, and the
            # post-drain durability + copy-parity audit reports
            "fenced_ops": delta("replication.fenced_ops"),
            "stale_primary_rejections":
                delta("replication.stale_primary_rejections"),
            "replication_rollbacks": delta("replication.rollbacks"),
            "resyncs": delta("replication.resyncs"),
            "durability": durability,
            "copy_parity": parity,
            "final_state": final,
            "query_insights": query_insights,
            # accelerator fault accounting (present only for device
            # soaks): breaker trips/states, sanity-guard discards, and
            # every degradation path's counters
            # elasticity accounting (present only for autoscale soaks)
            **({"autoscale": autoscale_report}
               if cfg.autoscale and autoscale_report is not None
               else {}),
            **({"device": {
                **device_report,
                "breaker_trips": delta("device.breaker.trips"),
                "breaker_closes": delta("device.breaker.closes"),
                "device_errors": delta("device.errors"),
                "poisoned": delta("device.poisoned_results"),
                "restage_failures": delta("device.restage_failures"),
                "host_fallbacks": delta("device.host_fallback"),
                "mesh_fallbacks": delta("search.mesh.fallback"),
                "degraded_searches": delta("device.degraded_searches"),
            }} if device_report is not None else {}),
        }

    def _run_concurrent(self, ops, by_step, ctx) -> None:
        """Full-config mode: ops run on a small worker pool in chunks;
        fault directives still apply at their op index, between chunks
        (coarser interleaving — the smoke config stays sequential for
        bit-exact determinism)."""
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(
                max_workers=self.config.concurrency,
                thread_name_prefix="soak-worker") as pool:
            i = 0
            while i < len(ops):
                chunk = ops[i:i + self.config.concurrency]
                for j in range(i, i + len(chunk)):
                    for d in by_step.get(j, []):
                        self._apply_fault(d, ctx)
                futs = [pool.submit(self._run_op, i + j, op, ctx)
                        for j, op in enumerate(chunk)]
                for f in futs:
                    f.result()
                i += len(chunk)

    def _final_state(self, ctx: dict) -> dict:
        """Post-drain doc count + content checksum via the normal search
        path, all-or-nothing (a shard that cannot answer here is a
        convergence failure, reported as such).  The raw id → source
        map is stashed in ``ctx["final_docs"]`` for the durability
        audit (it replays the write history against exactly this
        client-visible state)."""
        client = ctx["nodes"][ctx["client"]]
        try:
            resp = client.search(self.config.index, {
                "query": {"match_all": {}}, "size": 10_000,
                "allow_partial_search_results": False})
        except OpenSearchTpuError as exc:
            ctx["final_docs"] = None
            return {"error": f"{type(exc).__name__}: {exc}"}
        ctx["final_docs"] = {h["_id"]: h["_source"]
                             for h in resp["hits"]["hits"]}
        docs = sorted(
            (h["_id"], json.dumps(h["_source"], sort_keys=True))
            for h in resp["hits"]["hits"])
        return {"doc_count": resp["hits"]["total"]["value"],
                "checksum": zlib.crc32(
                    json.dumps(docs).encode("utf-8"))}

    def _copy_parity(self, ctx: dict) -> dict:
        """Per-copy convergence: after the drain, the primary, every
        in-sync replica, and every ready searcher of each shard must
        serve the same per-doc ``(seq_no, primary_term, version)``
        digest (``InternalEngine.replication_digest``).  Write copies
        compare the full term-aware digest; the search tier compares
        the termless ``seq_digest`` (its copies are rebuilt from
        segment checkpoints, same seq/version lineage).  Retries
        briefly — replicas install published checkpoints
        asynchronously — then reports the LAST snapshot; a persistent
        mismatch is an SLO breach, not a harness error."""
        cfg = self.config
        nodes = ctx["nodes"]

        def snapshot():
            state = nodes[ctx["leader"]].coordinator.state()
            shards, digests, all_ok = [], [], True
            for s, e in enumerate(state.routing.get(cfg.index, [])):
                primary = e.get("primary")
                copies, searchers = [], []
                try:
                    if primary not in nodes:
                        raise SoakHarnessError(f"primary [{primary}] gone")
                    copies.append((f"{primary}:primary", nodes[
                        primary].indices[cfg.index].engine_for(
                        s).replication_digest()))
                    for r in (e.get("replicas") or []):
                        if r in (e.get("in_sync") or []) and r in nodes:
                            copies.append((f"{r}:replica", nodes[
                                r].indices[cfg.index].engine_for(
                                s).replication_digest()))
                    for r in (e.get("search_in_sync") or []):
                        if r in nodes:
                            searchers.append((f"{r}:search", nodes[
                                r].indices[cfg.index].engine_for(
                                s).replication_digest()))
                except (OpenSearchTpuError, KeyError) as exc:
                    shards.append({"shard": s, "ok": False,
                                   "error": f"{type(exc).__name__}: "
                                            f"{exc}"})
                    all_ok = False
                    continue
                pdig = copies[0][1]
                write_ok = len({d["digest"] for _, d in copies}) == 1
                search_ok = all(d["seq_digest"] == pdig["seq_digest"]
                                for _, d in searchers)
                row = {"shard": s, "ok": write_ok and search_ok,
                       "copies": {lbl: {"digest": d["digest"],
                                        "seq_digest": d["seq_digest"],
                                        "doc_count": d["doc_count"]}
                                  for lbl, d in copies + searchers}}
                if not (write_ok and search_ok):
                    # diagnosable evidence: which doc positions differ
                    base = copies[0][1]["docs"]
                    for lbl, d in copies[1:] + searchers:
                        diff = sorted(
                            k for k in set(base) | set(d["docs"])
                            if base.get(k) != d["docs"].get(k))[:10]
                        if diff:
                            row.setdefault("diverged", {})[lbl] = diff
                shards.append(row)
                all_ok = all_ok and row["ok"]
                digests += [(f"{lbl}/s{s}", d["docs"])
                            for lbl, d in copies + searchers]
            return {"ok": all_ok, "shards": shards,
                    "copy_digests": digests}

        report = snapshot()
        deadline = time.monotonic() + 10.0
        while not report["ok"] and time.monotonic() < deadline:  # deadline
            time.sleep(0.05)                                     # deadline
            report = snapshot()
        return report

    def _durability_report(self, ctx: dict, copy_digests: list) -> dict:
        """Run the ``DurabilityChecker`` over the recorded history,
        the final client-visible state, and the per-copy digests; bump
        the audit counter so ``_nodes/stats`` / ``/_metrics`` show how
        many acked-write promises were actually verified."""
        from opensearch_tpu.testing.history import DurabilityChecker
        hist = ctx["history"]
        hist.settle_open_as_unknown("soak drain")
        final_docs = ctx.get("final_docs")
        if final_docs is None:
            return {"ok": False, "checked_ops": hist.checked_ops,
                    "error": "final state unavailable"}
        report = DurabilityChecker(hist).check(final_docs, copy_digests)
        metrics().counter("replication.durability_checked_ops").inc(
            report["checked_ops"])
        return report

    # -- SLO evaluation ----------------------------------------------------

    def _verdicts(self, chaos: dict, control: Optional[dict]) -> list:
        slos = self.config.slos
        verdicts = []
        for klass, limit in sorted(
                (slos.get("p99_ms") or {}).items()):
            observed = chaos["p99_ms"].get(klass, 0.0)
            verdicts.append({"slo": f"p99_ms.{klass}",
                             "limit": limit, "observed": observed,
                             "ok": observed <= limit})
        total_ops = max(chaos["ops"], 1)
        rate = round(chaos["rejected"] / total_ops, 4)
        max_rate = slos.get("max_rejection_rate", 1.0)
        verdicts.append({"slo": "rejection_rate", "limit": max_rate,
                         "observed": rate, "ok": rate <= max_rate})
        budget = slos.get("max_unexpected_errors", 0)
        verdicts.append({
            "slo": "unexpected_errors", "limit": budget,
            "observed": len(chaos["unexpected_errors"]),
            "ok": len(chaos["unexpected_errors"]) <= budget})
        if slos.get("require_convergence", True) and control is not None:
            ok = (chaos["final_state"] == control["final_state"]
                  and "error" not in chaos["final_state"])
            verdicts.append({
                "slo": "convergence",
                "limit": control["final_state"],
                "observed": chaos["final_state"], "ok": ok})
        dur = chaos.get("durability") or {}
        if slos.get("no_lost_acked_writes"):
            lost = dur.get("lost_acked_writes", [])
            checked = int(dur.get("checked_ops", 0))
            verdicts.append({
                "slo": "no_lost_acked_writes", "limit": 0,
                "observed": {"lost": len(lost),
                             "checked_ops": checked,
                             **({"evidence": lost[:5]} if lost else {})},
                # an audit that checked NOTHING (or errored) is a
                # breach, not a free pass
                "ok": (not lost and checked > 0
                       and "error" not in dur)})
        if slos.get("no_stale_acks"):
            stale = dur.get("stale_acks", [])
            mono = dur.get("monotonicity_violations", [])
            conflicts = dur.get("copy_conflicts", [])
            bad = len(stale) + len(mono) + len(conflicts)
            verdicts.append({
                "slo": "no_stale_acks", "limit": 0,
                "observed": {"stale_acks": len(stale),
                             "monotonicity": len(mono),
                             "copy_conflicts": len(conflicts),
                             **({"evidence":
                                 (stale + mono + conflicts)[:5]}
                                if bad else {})},
                "ok": bad == 0 and "error" not in dur})
        if slos.get("require_copy_parity"):
            par = chaos.get("copy_parity") or {}
            mismatched = [s for s in par.get("shards", [])
                          if not s.get("ok")]
            verdicts.append({
                "slo": "copy_parity", "limit": [],
                "observed": mismatched,
                "ok": par.get("ok", False)})
        dev = chaos.get("device") or {}
        if slos.get("require_breaker_trip"):
            trips = int(dev.get("breaker_trips", 0))
            verdicts.append({"slo": "device_breaker_trip", "limit": 1,
                             "observed": trips, "ok": trips >= 1})
        if slos.get("require_breaker_reclose"):
            # every breaker that tripped must be closed again after the
            # heal — except the mesh, which on a 1-device CPU host can
            # never rebuild and stays legitimately demoted
            states = dev.get("breaker_states") or {}
            stuck = sorted(k for k in dev.get("tripped", [])
                           if k != "mesh"
                           and states.get(k) != "closed")
            verdicts.append({"slo": "device_breaker_reclose",
                             "limit": [], "observed": stuck,
                             "ok": not stuck})
        if slos.get("require_poison_detected"):
            poisoned = int(dev.get("poisoned", 0))
            verdicts.append({"slo": "device_poison_detected",
                             "limit": 1, "observed": poisoned,
                             "ok": poisoned >= 1})
        auto = chaos.get("autoscale") or {}
        if slos.get("require_scale_up"):
            # >= 1 scale-up that ALSO appended to the audit ring — an
            # unaudited fleet mutation fails the SLO even if it scaled
            ups = int(auto.get("scale_ups", 0))
            audited = int(auto.get("decisions_audited", 0))
            verdicts.append({"slo": "autoscale_scale_up_audited",
                             "limit": 1, "observed": min(ups, audited),
                             "ok": ups >= 1 and audited >= 1})
        if slos.get("require_drain_complete"):
            done = int(auto.get("drains_completed", 0))
            verdicts.append({"slo": "autoscale_drain_complete",
                             "limit": 1, "observed": done,
                             "ok": done >= 1})
        return verdicts

    def _capture_breaches(self, verdicts: list, chaos: dict) -> None:
        """Every breached SLO verdict gets a flight-recorder capture
        attached — recent spans + counter snapshot + the breach's own
        limit/observed pair — so a red verdict ships with diagnosable
        evidence, not just a boolean (the captures are also retrievable
        later via ``GET /_nodes/flight_recorder``).  Determinism note:
        the smoke suite compares ``(slo, ok)`` pairs, never the capture
        payloads, which carry timestamps by design."""
        from opensearch_tpu.common.telemetry import flight_recorder
        for v in verdicts:
            if v["ok"]:
                continue
            v["flight_recorder"] = flight_recorder().record(
                "slo_breach",
                f"soak SLO [{v['slo']}] breached",
                detail={"slo": v["slo"], "limit": v["limit"],
                        "observed": v["observed"],
                        "seed": self.config.seed,
                        "applied_faults": [
                            {"step": d.get("step"),
                             "fault": d.get("fault")}
                            for d in chaos.get("applied", [])],
                        "unexpected_errors":
                            list(chaos.get("unexpected_errors", [])),
                        # the top-queries snapshot taken while the
                        # cluster was alive: WHAT was running when the
                        # SLO went red, by plan signature
                        "query_insights":
                            chaos.get("query_insights") or {}})

    def run(self) -> dict:
        """Control pass (when configured) then chaos pass, then SLO
        evaluation.  Always returns the report; ``slo_ok`` is the single
        pass/fail bit and ``verdicts`` carries every breach."""
        try:
            control = (self._run_once("control", inject=False)
                       if self.config.control_run
                       and self.config.faults_enabled else None)
            chaos = self._run_once(
                "chaos", inject=self.config.faults_enabled)
            verdicts = self._verdicts(chaos, control)
            self._capture_breaches(verdicts, chaos)
            return {
                "seed": self.config.seed,
                "config": {"n_ops": self.config.n_ops,
                           "n_docs": self.config.n_docs,
                           "nodes": list(self.config.node_ids),
                           "shards": self.config.shards,
                           "replicas": self.config.replicas,
                           "faults_enabled": self.config.faults_enabled},
                "control": control,
                "chaos": chaos,
                "verdicts": verdicts,
                "slo_ok": all(v["ok"] for v in verdicts),
            }
        finally:
            if self._own_dir:
                shutil.rmtree(self.data_path, ignore_errors=True)


class SoakHarnessError(OpenSearchTpuError):
    """The harness itself failed (timeout waiting on cluster plumbing) —
    distinct from an SLO breach, which is REPORTED in the verdicts."""


class SoakUnexpectedError(OpenSearchTpuError):
    """A client-visible failure outside the allowed degradation classes
    (429 / partial results) — draws against the zero-5xx budget."""


def run_soak(data_path: Optional[str] = None, *,
             full: bool = False, **overrides) -> dict:
    """One-call entry point (bench.py's ``soak`` phase)."""
    cfg = (SoakConfig.full(**overrides) if full
           else SoakConfig.smoke(**overrides))
    return SoakRunner(data_path, cfg).run()


def run_device_soak(data_path: Optional[str] = None,
                    **overrides) -> dict:
    """One-call entry point for the accelerator-fault soak (bench.py's
    ``device_faults`` phase, tests/test_device_faults.py acceptance)."""
    return SoakRunner(data_path, SoakConfig.device(**overrides)).run()


def run_autoscale_soak(data_path: Optional[str] = None,
                       **overrides) -> dict:
    """One-call entry point for the elasticity soak (bench.py's
    ``autoscale`` phase, tests/test_autoscaler.py acceptance): hot-
    tenant pressure scales the fleet up, the idle window drains it
    back, SLOs hold through both transitions."""
    return SoakRunner(
        data_path, SoakConfig.autoscale_churn(**overrides)).run()


# -- noisy-neighbor QoS scenario -------------------------------------------


class NoisyNeighborRunner(SoakRunner):
    """The per-tenant QoS soak: two tenants against one coordinator —
    a well-behaved victim issuing sequential zipf-tail searches, and an
    aggressor flooding the zipf HEAD in concurrent bursts that exceed
    its carved admission share many times over.  Every shard query
    phase is slowed by a seeded delay so the bursts genuinely overlap
    inside the admission window.

    SLOs assert ISOLATION, not absence of overload: the victim's p99
    and 429-rate hold while the aggressor's flood is shed at the
    admission gate (its own 429s), and the adaptive QoS controller —
    ticked deterministically once per op — records at least one
    adaptation (with its triggering evidence) in the audit ring.
    Same-seed runs produce identical verdicts (two-run determinism,
    pinned in tests/test_qos.py)."""

    VICTIM = "tenant-victim"
    AGGRESSOR = "tenant-aggressor"

    def __init__(self, data_path: Optional[str] = None,
                 config: Optional[SoakConfig] = None, *,
                 burst: int = 12, delay_s: float = 0.03,
                 admission_permits: int = 8,
                 victim_share: float = 6.0,
                 aggressor_share: float = 1.0,
                 slos: Optional[dict] = None):
        super().__init__(data_path, config or SoakConfig(
            seed=42, n_ops=16, n_docs=24, control_run=False))
        self.burst = int(burst)
        self.delay_s = float(delay_s)
        self.admission_permits = int(admission_permits)
        self.victim_share = float(victim_share)
        self.aggressor_share = float(aggressor_share)
        self.qos_slos = slos if slos is not None else {
            # generous CI-safe bounds: verdicts must be deterministic
            # across runs/hosts; observed values track the trajectory
            "victim_p99_ms": 10_000.0,
            "victim_max_429_rate": 0.0,
            "aggressor_min_429": 1,
            "min_qos_adaptations": 1,
            "max_unexpected_errors": 0,
        }

    @contextlib.contextmanager
    def _as_tenant(self, node, tenant: str):
        """Run the enclosed client calls under a registered task whose
        X-Opaque-Id names the tenant — the same header threading the
        REST edge performs, so admission, sheds, and insights all
        attribute to the tenant."""
        from opensearch_tpu.common import tasks as taskmod
        task = node.task_manager.register(
            "rest:noisy_neighbor", f"[{tenant}]",
            headers={"X-Opaque-Id": tenant})
        token = taskmod.set_current(task)
        try:
            yield
        finally:
            taskmod.reset_current(token)
            node.task_manager.unregister(task)

    def _flood(self, coord, index: str, body: dict, ctx: dict) -> None:
        """One aggressor burst: ``burst`` concurrent identical
        zipf-head searches released by a barrier, each under the
        aggressor tenant.  The per-tenant admission carve means most of
        the burst 429s while the victim's permits stay untouched."""
        barrier = threading.Barrier(self.burst)

        def one():
            barrier.wait(timeout=10.0)
            t0 = time.monotonic()
            try:
                with self._as_tenant(coord, self.AGGRESSOR):
                    coord.search(index, dict(body))
                _bump(ctx, "aggr_ok")
            except OpenSearchTpuError as exc:
                if getattr(exc, "status", 0) == 429:
                    _bump(ctx, "aggr_429")
                elif self._retryable(exc):
                    _bump(ctx, "client_retries")
                else:
                    with ctx["lock"]:
                        ctx["unexpected"].append(
                            f"aggressor: {type(exc).__name__}: {exc}")
            finally:
                ctx["hists"]["aggressor"].observe(
                    (time.monotonic() - t0) * 1000.0)
        threads = [threading.Thread(target=one,
                                    name=f"noisy-aggr-{i}",
                                    daemon=True)
                   for i in range(self.burst)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30.0)

    def run(self) -> dict:    # noqa: C901 — one linear scenario
        from opensearch_tpu.cluster import response_collector as rc_mod
        from opensearch_tpu.search import engine as engine_mod
        from opensearch_tpu.testing.fault_injection import FaultInjector
        from opensearch_tpu.transport.service import LocalTransport

        cfg = self.config
        root = f"{self.data_path}/noisy"
        hub = LocalTransport.Hub()
        nodes = {nid: self._build_node(hub, nid, root)
                 for nid in cfg.node_ids}
        # the coordinator-only client: no shards, so every shard query
        # phase crosses the transport hub and the seeded delay applies
        coord_id = "c0"
        nodes[coord_id] = self._build_node(hub, coord_id, root,
                                           roles=("master",))
        coord = nodes[coord_id]
        ctx = {
            "lock": threading.Lock(),
            "hists": {"victim": Histogram("noisy.victim"),
                      "aggressor": Histogram("noisy.aggressor")},
            "victim_ok": 0, "victim_429": 0,
            "aggr_ok": 0, "aggr_429": 0,
            "client_retries": 0, "unexpected": [],
        }
        # adaptive knobs are process-global module settings: save and
        # restore so the scenario leaves no trace in the suite
        saved_shed = rc_mod.SHED_OCCUPANCY
        saved_window = engine_mod.AUTO_WINDOW_MS
        faults = FaultInjector(hub, seed=cfg.seed)
        try:
            leader = cfg.node_ids[0]
            if not nodes[leader].start_election():
                raise SoakHarnessError("initial election failed")
            self._wait(lambda: all(
                nodes[i].coordinator.state().master_node == leader
                for i in cfg.node_ids), what="initial leader convergence")
            nodes[leader].coordinator.add_node(
                coord_id, {"name": coord_id, "roles": ["master"],
                           "master_eligible": True})
            self._wait(lambda: coord_id in
                       coord.coordinator.state().nodes,
                       what="coordinator-only node joining")
            nodes[leader].create_index(cfg.index, {
                "settings": {"number_of_shards": cfg.shards,
                             "number_of_replicas": cfg.replicas},
                "mappings": {"properties": {
                    "body": {"type": "text"}, "v": {"type": "long"}}}})
            self._wait(lambda: self._in_sync_full(nodes, leader),
                       what="initial shard allocation")
            workload = MixedWorkload(cfg)
            for doc_id, source in workload.seed_docs():
                nodes[leader].index_doc(cfg.index, doc_id,
                                        {"body": source["body"],
                                         "v": source["v"]})
            nodes[leader].refresh(cfg.index)

            # per-tenant QoS on the coordinator: a small carved budget
            # (aggressor gets ~1 permit), the adaptive controller armed
            # with single-tick hysteresis and a shed threshold it can
            # demonstrably walk down
            adm = coord.search_backpressure.admission
            adm.max_concurrent = self.admission_permits
            adm.set_tenant_shares({self.VICTIM: self.victim_share,
                                   self.AGGRESSOR: self.aggressor_share})
            coord.qos.set_enabled(True)
            coord.qos.hysteresis_ticks = 1
            rc_mod.SHED_OCCUPANCY = 0.5
            # seeded slowdown on every data node's query phase so the
            # aggressor's bursts genuinely overlap in the gate
            for nid in cfg.node_ids:
                faults.slow_search_node(nid, self.delay_s)

            queries = zipf_query_log(max(16, cfg.n_ops), cfg.vocab_size,
                                     seed=cfg.seed)
            head_body = {"query": {"match": {"body": "t0 t1"}},
                         "size": 10}
            qi = 0
            for i in range(cfg.n_ops):
                if i % 4 == 3:
                    self._flood(coord, cfg.index, head_body, ctx)
                else:
                    a, b = queries[qi % len(queries)]
                    qi += 1
                    body = {"query": {"match": {"body": f"t{a} t{b}"}},
                            "size": 10}
                    t0 = time.monotonic()
                    try:
                        with self._as_tenant(coord, self.VICTIM):
                            coord.search(cfg.index, body)
                        _bump(ctx, "victim_ok")
                    except OpenSearchTpuError as exc:
                        if getattr(exc, "status", 0) == 429:
                            _bump(ctx, "victim_429")
                        else:
                            ctx["unexpected"].append(
                                f"victim op {i}: "
                                f"{type(exc).__name__}: {exc}")
                    finally:
                        ctx["hists"]["victim"].observe(
                            (time.monotonic() - t0) * 1000.0)
                # deterministic controller pacing: exactly one
                # evaluation per op, so the adaptation count is a pure
                # function of the op stream's admission evidence
                coord.qos.run_once()

            report = self._qos_report(coord, ctx)
        finally:
            rc_mod.SHED_OCCUPANCY = saved_shed
            engine_mod.AUTO_WINDOW_MS = saved_window
            faults.clear()
            for n in list(nodes.values()):
                n.stop()
            if self._own_dir:
                shutil.rmtree(self.data_path, ignore_errors=True)
        return report

    def _qos_report(self, coord, ctx: dict) -> dict:
        slos = self.qos_slos
        victim_ops = ctx["victim_ok"] + ctx["victim_429"]
        victim_rate = (ctx["victim_429"] / victim_ops
                       if victim_ops else 0.0)
        victim_p99 = ctx["hists"]["victim"].percentile(99)
        qos_stats = coord.qos.stats()
        verdicts = [
            {"slo": "victim_p99_ms", "limit": slos["victim_p99_ms"],
             "observed": round(victim_p99, 3),
             "ok": victim_p99 <= slos["victim_p99_ms"]},
            {"slo": "victim_429_rate",
             "limit": slos["victim_max_429_rate"],
             "observed": round(victim_rate, 4),
             "ok": victim_rate <= slos["victim_max_429_rate"]},
            {"slo": "aggressor_shed",
             "limit": slos["aggressor_min_429"],
             "observed": ctx["aggr_429"],
             "ok": ctx["aggr_429"] >= slos["aggressor_min_429"]},
            {"slo": "qos_adaptations",
             "limit": slos["min_qos_adaptations"],
             "observed": qos_stats["adaptations"],
             "ok": (qos_stats["adaptations"]
                    >= slos["min_qos_adaptations"])},
            {"slo": "unexpected_errors",
             "limit": slos["max_unexpected_errors"],
             "observed": len(ctx["unexpected"]),
             "ok": (len(ctx["unexpected"])
                    <= slos["max_unexpected_errors"])},
        ]
        return {
            "seed": self.config.seed,
            "ops": self.config.n_ops,
            "burst": self.burst,
            "tenants": {
                self.VICTIM: {
                    "ops": victim_ops, "ok": ctx["victim_ok"],
                    "rejected": ctx["victim_429"],
                    "p99_ms": round(victim_p99, 3)},
                self.AGGRESSOR: {
                    "ops": ctx["aggr_ok"] + ctx["aggr_429"],
                    "ok": ctx["aggr_ok"],
                    "rejected": ctx["aggr_429"],
                    "p99_ms": round(
                        ctx["hists"]["aggressor"].percentile(99), 3)},
            },
            "client_retries": ctx["client_retries"],
            "unexpected_errors": list(ctx["unexpected"]),
            "admission": coord.search_backpressure.admission.stats(),
            "insights_tenants": coord.insights.tenants(),
            "qos": qos_stats,
            "verdicts": verdicts,
            "slo_ok": all(v["ok"] for v in verdicts),
        }


def run_noisy_neighbor(data_path: Optional[str] = None,
                       **overrides) -> dict:
    """One-call entry point for the noisy-neighbor QoS scenario
    (bench.py's ``qos`` phase, tests/test_qos.py's acceptance)."""
    cfg_keys = {"seed", "n_ops", "n_docs", "shards", "replicas",
                "vocab_size"}
    cfg_over = {k: v for k, v in overrides.items() if k in cfg_keys}
    run_over = {k: v for k, v in overrides.items() if k not in cfg_keys}
    cfg = SoakConfig(control_run=False,
                     **{"seed": 42, "n_ops": 16, "n_docs": 24,
                        **cfg_over})
    return NoisyNeighborRunner(data_path, cfg, **run_over).run()
