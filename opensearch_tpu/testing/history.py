"""Acked-write durability audit: a history-recording CRUD client plus
the post-drain checker.

Analog of the Jepsen-style histories the reference's replication work
was validated against (and of its own ``AbstractDisruptionTestCase``
acked-write assertions): every write the workload issues is recorded as
an interval — ``invoke`` when it leaves the client, then exactly one of

- ``ok``       the cluster ACKED it (with the ``(primary_term, seq_no,
               version)`` triple from the response): a durability
               promise that must survive every later failover,
- ``fail``     the cluster DEFINITELY rejected it (a fence 503 raised
               instead of an ack, a version conflict): the write must
               never become visible,
- ``unknown``  the outcome is indeterminate (timeout, partition,
               retries exhausted): the write may or may not survive —
               both final states are legal.

After the soak drains, ``DurabilityChecker`` replays the history
against the cluster's final visible state and the per-copy replication
digests (``InternalEngine.replication_digest``) and asserts the
replication-safety contract:

- **no lost acked writes** — a doc whose last settled op was an acked
  index (with no later-starting op that could supersede it) is present
  with exactly the acked content; an acked delete stays deleted,
- **no stale acks / failed writes visible** — content recorded only
  under ``fail`` outcomes never appears in the final state,
- **per-doc ``(primary_term, seq_no)`` monotonicity** — over
  non-overlapping acked ops on one doc, the term-seq pair never goes
  backwards (a fenced old primary cannot re-ack under its stale term),
- **cross-copy parity** — no two copies hold the same ``(seq_no,
  primary_term)`` for a doc with different content (the split-brain
  signature fencing exists to prevent).

The recorder is deliberately dumb and thread-safe: a list of dicts
under a lock, a global monotone event counter for interval ordering.
Everything here is deterministic given a deterministic workload — the
checker's report feeds the soak's ``no_lost_acked_writes`` /
``no_stale_acks`` SLO verdicts, which tier-1 replays seed-for-seed.
"""

from __future__ import annotations

import json
import threading
from typing import Optional

__all__ = ["HistoryRecorder", "DurabilityChecker", "canonical"]


def canonical(source: Optional[dict]) -> str:
    """Canonical content key: sorted compact JSON (None for deletes)."""
    if source is None:
        return "<deleted>"
    return json.dumps(source, sort_keys=True, separators=(",", ":"))


class HistoryRecorder:
    """Interval history of CRUD ops (invoke → ok | fail | unknown)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._events = 0          # global monotone interval clock
        self.ops: list[dict] = []

    def _tick(self) -> int:
        self._events += 1
        return self._events

    def invoke(self, op: str, doc_id: str,
               source: Optional[dict] = None) -> int:
        """Record an outbound write; returns the op id to settle with.
        ``op`` is ``index`` or ``delete``; ``source`` the exact body."""
        with self._lock:
            op_id = len(self.ops)
            self.ops.append({
                "op_id": op_id, "op": op, "doc_id": str(doc_id),
                "content": canonical(source if op == "index" else None),
                "outcome": None, "invoked_at": self._tick(),
                "settled_at": None, "seq_no": None,
                "primary_term": None, "version": None, "detail": None,
            })
            return op_id

    def _settle(self, op_id: int, outcome: str, detail=None,
                resp: Optional[dict] = None):
        with self._lock:
            rec = self.ops[op_id]
            if rec["outcome"] is not None:      # first settle wins
                return
            rec["outcome"] = outcome
            rec["settled_at"] = self._tick()
            rec["detail"] = detail
            if resp:
                for k, field in (("_seq_no", "seq_no"),
                                 ("_primary_term", "primary_term"),
                                 ("_version", "version")):
                    if resp.get(k) is not None:
                        rec[field] = int(resp[k])

    def ok(self, op_id: int, resp: Optional[dict] = None):
        self._settle(op_id, "ok", resp=resp or {})

    def fail(self, op_id: int, why: str = ""):
        self._settle(op_id, "fail", detail=why)

    def unknown(self, op_id: int, why: str = ""):
        self._settle(op_id, "unknown", detail=why)

    def settle_open_as_unknown(self, why: str = "run ended mid-flight"):
        """Drain hygiene: any interval never settled (worker died, run
        aborted) is UNKNOWN, never silently dropped."""
        with self._lock:
            pending = [r["op_id"] for r in self.ops
                       if r["outcome"] is None]
        for op_id in pending:
            self.unknown(op_id, why)

    @property
    def checked_ops(self) -> int:
        with self._lock:
            return len(self.ops)

    def counts(self) -> dict:
        with self._lock:
            out = {"ok": 0, "fail": 0, "unknown": 0}
            for r in self.ops:
                out[r["outcome"] or "unknown"] += 1
            out["total"] = len(self.ops)
            return out

    def snapshot(self) -> list:
        with self._lock:
            return [dict(r) for r in self.ops]


class DurabilityChecker:
    """Post-drain audit of a ``HistoryRecorder`` against final state."""

    def __init__(self, history: HistoryRecorder):
        self.history = history

    def check(self, final_docs: dict,
              copy_digests: Optional[list] = None) -> dict:
        """``final_docs``: doc_id → source from the post-drain search
        (the client-visible final state).  ``copy_digests``: optional
        ``[(label, digest_docs), ...]`` where digest_docs is the
        ``docs`` map of ``replication_digest()`` — used for the
        duplicate-``(term, seq)``-differing-content cross-copy check.
        Returns the report; ``ok`` is the single verdict bit and every
        violation ships with its evidence."""
        ops = self.history.snapshot()
        final = {str(k): canonical(v) for k, v in final_docs.items()}
        by_doc: dict[str, list] = {}
        for r in ops:
            by_doc.setdefault(r["doc_id"], []).append(r)

        lost_acked: list[dict] = []
        stale_acks: list[dict] = []
        monotonicity: list[dict] = []
        for doc_id, recs in sorted(by_doc.items()):
            recs = sorted(recs, key=lambda r: r["invoked_at"])
            acked = [r for r in recs if r["outcome"] == "ok"]
            # -- lost acked writes: the LAST acked op, unless an op that
            # could supersede it (ok or unknown) was invoked after it
            # settled, pins the doc's final state
            if acked:
                last = max(acked, key=lambda r: r["settled_at"])
                superseded = any(
                    r["invoked_at"] > last["settled_at"] for r in recs
                    if r["outcome"] in ("ok", "unknown")
                    and r is not last)
                if not superseded:
                    want = (last["content"] if last["op"] == "index"
                            else "<deleted>")
                    got = final.get(doc_id, "<deleted>")
                    if got != want:
                        lost_acked.append({
                            "doc_id": doc_id, "op": last["op"],
                            "acked": want, "final": got,
                            "seq_no": last["seq_no"],
                            "primary_term": last["primary_term"]})
            # -- stale acks: content visible in the final state that was
            # only ever written by ops recorded as DEFINITE failures
            got = final.get(doc_id)
            if got is not None:
                could_have_written = {
                    r["content"] for r in recs
                    if r["op"] == "index"
                    and r["outcome"] in ("ok", "unknown")}
                failed_wrote = {r["content"] for r in recs
                                if r["op"] == "index"
                                and r["outcome"] == "fail"}
                if got in failed_wrote and got not in could_have_written:
                    stale_acks.append({
                        "doc_id": doc_id, "final": got,
                        "failed_ops": [r["op_id"] for r in recs
                                       if r["outcome"] == "fail"
                                       and r["content"] == got]})
            # -- (primary_term, seq_no) monotone over non-overlapping
            # acked ops (B invoked after A settled must not ack behind A)
            with_pos = [r for r in acked if r["seq_no"] is not None]
            for i, a in enumerate(with_pos):
                for b in with_pos[i + 1:]:
                    if b["invoked_at"] <= a["settled_at"]:
                        continue            # concurrent: order unknowable
                    pa = (a["primary_term"] or 1, a["seq_no"])
                    pb = (b["primary_term"] or 1, b["seq_no"])
                    if pb <= pa:
                        monotonicity.append({
                            "doc_id": doc_id,
                            "earlier": {"op_id": a["op_id"], "pos": pa},
                            "later": {"op_id": b["op_id"], "pos": pb}})

        # -- cross-copy duplicate (seq, term) with differing content:
        # two copies serving the same position with different bytes is
        # the split-brain divergence signature
        copy_conflicts: list[dict] = []
        for i, (la, da) in enumerate(copy_digests or []):
            for lb, db in (copy_digests or [])[i + 1:]:
                for doc_id in sorted(set(da) & set(db)):
                    a, b = da[doc_id], db[doc_id]
                    # digest rows are [seq, term, version, crc]
                    if tuple(a[:2]) == tuple(b[:2]) and a != b:
                        copy_conflicts.append({
                            "doc_id": doc_id, "pos": list(a[:2]),
                            "copies": {la: list(a), lb: list(b)}})

        counts = self.history.counts()
        return {
            "checked_ops": counts["total"],
            "outcomes": counts,
            "lost_acked_writes": lost_acked,
            "stale_acks": stale_acks,
            "monotonicity_violations": monotonicity,
            "copy_conflicts": copy_conflicts,
            "ok": not (lost_acked or stale_acks or monotonicity
                       or copy_conflicts),
        }
