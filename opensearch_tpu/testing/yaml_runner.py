"""YAML REST conformance runner: executes the reference's rest-api-spec
YAML suites verbatim against a running node.

Analog of ``OpenSearchClientYamlSuiteTestCase`` (ref test/framework/src/
main/java/org/opensearch/test/rest/yaml/
OpenSearchClientYamlSuiteTestCase.java:85) with the same execution model:
each suite file is a set of tests, each test a list of executable
sections — ``do`` (an API call resolved through the rest-api-spec api
JSON definitions, ref rest-api-spec/src/main/resources/rest-api-spec/
api/), assertions (``match``, ``length``, ``is_true``, ``is_false``,
``gt``/``gte``/``lt``/``lte``), a stash (``set`` / ``$var``
substitution), and ``catch`` for expected errors.  SURVEY §4.5 calls
these suites "the machine-checkable compatibility target".
"""

from __future__ import annotations

import json
import re
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass, field as dc_field

import yaml

# skip-features the runner implements; a test declaring anything else is
# reported as skipped, never silently passed
SUPPORTED_FEATURES = {"stash_in_key", "stash_in_path", "stash_path_replace",
                      "contains", "close_to"}

def _json_date(o):
    """YAML parses bare ISO timestamps into datetime objects; they ship
    as the ISO string the author wrote."""
    import datetime

    if isinstance(o, (datetime.datetime, datetime.date)):
        s = o.isoformat()
        return s.replace("+00:00", "Z")
    raise TypeError(f"not JSON serializable: {o!r}")


_CATCH_STATUS = {"bad_request": (400, 400), "unauthorized": (401, 401),
                 "forbidden": (403, 403), "missing": (404, 404),
                 "request_timeout": (408, 408), "conflict": (409, 409),
                 "unavailable": (503, 503), "param": (400, 400),
                 "request": (400, 599)}


@dataclass
class StepResult:
    test: str
    ok: bool
    skipped: bool = False
    message: str = ""


@dataclass
class ApiSpecs:
    """Lazy loader over rest-api-spec/api/*.json."""

    api_dir: str
    _cache: dict = dc_field(default_factory=dict)

    def get(self, name: str) -> dict:
        spec = self._cache.get(name)
        if spec is None:
            import os

            with open(os.path.join(self.api_dir, name + ".json")) as f:
                spec = json.load(f)[name]
            self._cache[name] = spec
        return spec

    def resolve(self, name: str, params: dict):
        """(method, path, query, body_allowed): picks the path variant
        with the most satisfied path parts (the official runner's
        best-match rule), leaving the rest as query params."""
        spec = self.get(name)
        best = None
        for p in spec["url"]["paths"]:
            parts = set(p.get("parts") or ())
            if not parts <= set(params):
                continue
            if best is None or len(parts) > len(best[0]):
                best = (parts, p)
        if best is None:
            raise ValueError(f"no path of [{name}] matches {sorted(params)}")
        parts, p = best
        path = p["path"]
        for part in parts:
            v = params[part]
            if isinstance(v, list):          # multi-index: /a,b/_refresh
                v = ",".join(str(x) for x in v)
            path = path.replace("{" + part + "}",
                                urllib.parse.quote(str(v), safe=","))
        query = {k: v for k, v in params.items()
                 if k not in parts and k != "body"}
        methods = p["methods"]
        method = methods[0]
        if "body" in params and params["body"] is not None \
                and "GET" in methods and "POST" in methods:
            method = "POST"          # bodies ride POST when both exist
        return method, path, query


class YamlRunner:
    """Executes one suite file's tests against ``base_url``."""

    def __init__(self, base_url: str, api_specs: ApiSpecs):
        self.base_url = base_url.rstrip("/")
        self.specs = api_specs

    # -- http -------------------------------------------------------------

    def _call(self, method, path, query, body, headers=None):
        url = self.base_url + path
        if query:
            url += "?" + urllib.parse.urlencode(
                {k: (str(v).lower() if isinstance(v, bool)
                     else ",".join(str(x) for x in v)
                     if isinstance(v, list) else v)
                 for k, v in query.items()})
        data = None
        hdrs = {"Content-Type": "application/json"}
        if body is not None:
            if isinstance(body, list):       # ndjson (bulk / msearch)
                data = ("\n".join(
                    x if isinstance(x, str) else json.dumps(x)
                    for x in body) + "\n").encode()
                hdrs["Content-Type"] = "application/x-ndjson"
            elif isinstance(body, str):
                data = body.encode()
            else:
                data = json.dumps(body, default=_json_date).encode()
        hdrs.update(headers or {})
        req = urllib.request.Request(url, data=data, method=method,
                                     headers=hdrs)
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                return r.status, self._parse(r.read(),
                                             r.headers.get("Content-Type"))
        except urllib.error.HTTPError as e:
            return e.code, self._parse(e.read(),
                                       e.headers.get("Content-Type"))

    @staticmethod
    def _parse(raw: bytes, ctype):
        return YamlRunner._parse_impl(raw, ctype)

    @staticmethod
    def _parse_impl(raw: bytes, ctype):
        if ctype and "json" in ctype:
            return json.loads(raw) if raw else {}
        return raw.decode(errors="replace")

    # -- suite execution --------------------------------------------------

    def run_file(self, path: str) -> list[StepResult]:
        with open(path) as f:
            docs = list(yaml.safe_load_all(f))
        setup = teardown = None
        tests = []
        for doc in docs:
            if not doc:
                continue
            for name, steps in doc.items():
                if name == "setup":
                    setup = steps
                elif name == "teardown":
                    teardown = steps
                else:
                    tests.append((name, steps))
        results = []
        for name, steps in tests:
            results.append(self._run_test(name, steps, setup, teardown))
        return results

    def _run_test(self, name, steps, setup, teardown) -> StepResult:
        self.stash: dict = {}
        self.last = None
        self.last_status = None
        try:
            skip_msg = self._skip_reason(steps)
            if skip_msg:
                return StepResult(name, ok=True, skipped=True,
                                  message=skip_msg)
            if setup:
                for step in setup:
                    self._step(step)
            try:
                for step in steps:
                    self._step(step)
            finally:
                if teardown:
                    for step in teardown:
                        self._step(step)
                self._wipe()
            return StepResult(name, ok=True)
        except AssertionError as e:
            return StepResult(name, ok=False, message=str(e))
        except Exception as e:  # noqa: BLE001 — report, don't crash the run
            return StepResult(name, ok=False,
                              message=f"{type(e).__name__}: {e}")

    def _wipe(self):
        """Between-tests cleanup (the runner's wipeCluster analog):
        delete every concrete index and template."""
        status, resp = self._call("GET", "/_cat/indices",
                                  {"format": "json"}, None)
        if status == 200 and isinstance(resp, list):
            for row in resp:
                name = row.get("index")
                if name:
                    self._call("DELETE",
                               "/" + urllib.parse.quote(name, safe=""),
                               {}, None)
        status, resp = self._call("GET", "/_template", {}, None)
        if status == 200 and isinstance(resp, dict):
            for name in resp:
                self._call("DELETE", f"/_template/{name}", {}, None)

    def _skip_reason(self, steps):
        for step in steps:
            if "skip" in step:
                sk = step["skip"] or {}
                feats = sk.get("features") or []
                if isinstance(feats, str):
                    feats = [feats]
                unsupported = [f for f in feats
                               if f not in SUPPORTED_FEATURES]
                if unsupported:
                    return f"features {unsupported}"
                version = str(sk.get("version", ""))
                if version.strip().lower() == "all":
                    return sk.get("reason", "skip all")
                # legacy numeric ranges target ES 6/7-era gaps; the
                # implementation under test is current, so they don't
                # apply
        return None

    # -- steps ------------------------------------------------------------

    def _step(self, step: dict):
        ((kind, body),) = step.items() if len(step) == 1 else (
            ("do", step.get("do")),)
        if kind == "skip":
            return
        if kind == "do":
            return self._do(body)
        if kind == "set":
            ((path, var),) = body.items()
            self.stash[var] = self._extract(path)
            return
        if kind == "match":
            ((path, expect),) = body.items()
            got = self._extract(path)
            expect = self._sub(expect)
            if (isinstance(expect, str) and len(expect) > 2
                    and expect.lstrip().startswith("/")
                    and expect.rstrip().endswith("/")):
                pat = expect.strip().strip("/")
                assert re.search(pat, str(got), re.X | re.S), \
                    f"match {path}: /{pat}/ !~ {got!r}"
            elif isinstance(expect, float) and isinstance(got, (int, float)):
                assert abs(float(got) - expect) < 1e-6 or got == expect, \
                    f"match {path}: expected {expect!r}, got {got!r}"
            else:
                assert _eq(got, expect), \
                    f"match {path}: expected {expect!r}, got {got!r}"
            return
        if kind == "contains":
            ((path, expect),) = body.items()
            got = self._extract(path)
            expect = self._sub(expect)
            ok = (expect in got if not isinstance(expect, dict)
                  else any(_eq(x, expect) for x in got))
            assert ok, f"contains {path}: {expect!r} not in {got!r}"
            return
        if kind == "length":
            ((path, expect),) = body.items()
            got = self._extract(path)
            assert len(got) == int(self._sub(expect)), \
                f"length {path}: expected {expect}, got {len(got)}"
            return
        if kind in ("is_true", "is_false"):
            try:
                got = self._extract(body)
            except AssertionError:
                got = None               # absent path is falsy (official
                # runner: is_false passes on a missing field)
            truthy = got not in (None, False, 0, "", "false") \
                and got != {}
            assert truthy == (kind == "is_true"), \
                f"{kind} {body}: got {got!r}"
            return
        if kind in ("gt", "gte", "lt", "lte"):
            ((path, expect),) = body.items()
            got = float(self._extract(path))
            expect = float(self._sub(expect))
            ok = {"gt": got > expect, "gte": got >= expect,
                  "lt": got < expect, "lte": got <= expect}[kind]
            assert ok, f"{kind} {path}: got {got}, bound {expect}"
            return
        if kind == "close_to":
            ((path, spec),) = body.items()
            got = float(self._extract(path))
            assert abs(got - float(spec["value"])) <= float(
                spec.get("error", 1e-6)), f"close_to {path}: {got}"
            return
        raise ValueError(f"unsupported section [{kind}]")

    def _do(self, body: dict):
        body = dict(body)
        catch = body.pop("catch", None)
        headers = self._sub(body.pop("headers", None))
        body.pop("warnings", None)
        body.pop("allowed_warnings", None)
        body.pop("node_selector", None)
        ((api, raw_params),) = body.items()
        params = self._sub(raw_params or {})
        req_body = params.pop("body", None) if isinstance(params, dict) \
            else None
        try:
            method, path, query = self.specs.resolve(api, {**params,
                                                           "body": req_body})
        except ValueError:
            # unresolvable path = client-side validation failure — what
            # `catch: param` asserts (the official runner raises the
            # same from its request builder)
            if catch == "param":
                return
            raise
        ignore = query.pop("ignore", None)
        status, resp = self._call(method, path, query, req_body, headers)
        self.last, self.last_status = resp, status
        if method == "HEAD":
            # HEAD APIs are booleans in the official client: 404 is a
            # `false` response, not an error
            self.last = status == 200
            if catch is None:
                assert status in (200, 404), f"{api} -> {status}"
                return
        if ignore is not None and status == int(ignore):
            return
        if catch is None:
            assert status < 400, \
                f"{api} -> {status}: {json.dumps(resp)[:300]}"
            return
        if catch.startswith("/"):
            assert status >= 400, f"{api}: expected error, got {status}"
            # catch regexes are compiled WITHOUT comments mode (spaces are
            # literal), unlike match assertions (DoSection vs MatchAssertion)
            pat = catch.strip("/")
            assert re.search(pat, json.dumps(resp), re.S), \
                f"{api}: /{pat}/ !~ {json.dumps(resp)[:300]}"
            return
        lo, hi = _CATCH_STATUS.get(catch, (400, 599))
        assert lo <= status <= hi, \
            f"{api}: catch {catch} expected {lo}-{hi}, got {status} " \
            f"{json.dumps(resp)[:200]}"

    # -- paths & stash ----------------------------------------------------

    def _extract(self, path):
        if path in ("$body", ""):
            return self.last
        node = self.last
        for part in _split_path(str(self._sub(path))):
            if isinstance(node, list):
                node = node[int(part)]
            elif isinstance(node, dict):
                if part not in node:
                    raise AssertionError(
                        f"path [{path}]: missing [{part}] in "
                        f"{json.dumps(node)[:200]}")
                node = node[part]
            else:
                raise AssertionError(f"path [{path}]: hit leaf at "
                                     f"[{part}]")
        return node

    def _sub(self, v):
        """Recursive $stash substitution."""
        if isinstance(v, str):
            if v.startswith("$"):
                key = v[1:]
                if key in self.stash:
                    return self.stash[key]
            return re.sub(r"\$\{(\w+)\}",
                          lambda m: str(self.stash.get(m.group(1),
                                                       m.group(0))), v)
        if isinstance(v, dict):
            return {self._sub(k) if isinstance(k, str) else k:
                    self._sub(x) for k, x in v.items()}
        if isinstance(v, list):
            return [self._sub(x) for x in v]
        return v


def _split_path(path: str) -> list[str]:
    """Dotted path with \\. escapes (field names containing dots)."""
    out, cur, i = [], "", 0
    while i < len(path):
        c = path[i]
        if c == "\\" and i + 1 < len(path) and path[i + 1] == ".":
            cur += "."
            i += 2
            continue
        if c == ".":
            out.append(cur)
            cur = ""
        else:
            cur += c
        i += 1
    out.append(cur)
    return [p for p in out if p != ""]


def _expand_dotted(d):
    """Dotted keys in an expected map address nested values (the Java
    runner resolves them via ObjectPath before comparing)."""
    out = {}
    for k, v in d.items():
        v = _expand_dotted(v) if isinstance(v, dict) else v
        if isinstance(k, str) and "." in k:
            node = out
            parts = k.split(".")
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = v
        else:
            out[k] = v
    return out


def _eq(got, expect) -> bool:
    """YAML-runner equality: ints/floats compare numerically; dotted keys
    in expected maps expand into nested paths; None only equals None."""
    if isinstance(expect, (int, float)) and isinstance(got, (int, float)) \
            and not isinstance(expect, bool) and not isinstance(got, bool):
        return float(got) == float(expect)
    if isinstance(expect, dict) and isinstance(got, dict):
        e, g = _expand_dotted(expect), got
        if set(e) != set(g):
            return False
        return all(_eq(g[k], e[k]) for k in e)
    return got == expect
