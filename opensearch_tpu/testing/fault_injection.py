"""Deterministic fault injection over the LocalTransport hub.

Analog of the test framework's ``MockTransportService`` +
``NetworkDisruption`` (test/framework .../test/transport/
MockTransportService.java, .../test/disruption/NetworkDisruption.java):
first-class drop / delay / duplicate / disconnect rules that match on
the transport ACTION NAME (glob patterns), scoped one-shot or sticky,
with every probabilistic choice drawn from a seeded RNG — the same seed
replays the same fault schedule, so every fault-tolerance test in this
repo is reproducible bit-for-bit.

Usage::

    hub = LocalTransport.Hub()
    faults = FaultInjector(hub, seed=42)
    faults.drop("indices:data/read/search*", target="n2", times=1)
    faults.delay(0.2, action="internal:coordination/*")
    faults.disconnect("n2")          # full partition
    faults.heal("n2")                # lift it
    faults.partition({"n0"}, {"n1", "n2"})   # symmetric two-sided split
    faults.heal_partition()          # reconnect the halves
    faults.clear()                   # lift everything
"""

from __future__ import annotations

import fnmatch
import os
import random
import threading
import time
from typing import Optional

from opensearch_tpu.common.errors import NodeDisconnectedError
from opensearch_tpu.transport.service import Directive, peek_action


class _Rule:
    """One installed fault: match → act, ``times``-bounded or sticky."""

    def __init__(self, injector: "FaultInjector", action: str,
                 source: Optional[str], target: Optional[str],
                 probability: float, times: Optional[int]):
        self.injector = injector
        self.action = action
        self.source = source
        self.target = target
        self.probability = float(probability)
        self.remaining = times           # None = sticky
        self._lock = threading.Lock()

    def matches(self, src: str, dst: str, frame: bytes) -> bool:
        if self.source is not None and src != self.source:
            return False
        if self.target is not None and dst != self.target:
            return False
        if self.action not in ("*", None):
            act = peek_action(frame)
            # exact match first: real action names contain glob
            # metacharacters ("...shard[r]"), which fnmatch would
            # otherwise read as a character class
            if act != self.action \
                    and not fnmatch.fnmatch(act, self.action):
                return False
        with self._lock:
            if self.remaining is not None and self.remaining <= 0:
                return False
            if self.probability < 1.0 \
                    and self.injector._random() >= self.probability:
                return False
            if self.remaining is not None:
                self.remaining -= 1
        return True

    def __call__(self, src: str, dst: str, frame: bytes):
        if self.matches(src, dst, frame):
            return self.act(src, dst)
        return None

    def act(self, src: str, dst: str):   # pragma: no cover - overridden
        return None


class _Drop(_Rule):
    def __init__(self, *a, silent: bool = False):
        super().__init__(*a)
        self.silent = silent

    def act(self, src, dst):
        if self.silent:
            # swallow: the sender's future just never resolves (times
            # out) — the lost-frame failure mode, vs. the fast-failing
            # connection-refused one below
            return Directive(copies=0)
        raise NodeDisconnectedError(
            f"[fault_injection] dropped frame {src}->{dst}")


class _Delay(_Rule):
    def __init__(self, *a, seconds: float):
        super().__init__(*a)
        self.seconds = float(seconds)

    def act(self, src, dst):
        return self.seconds


class _Duplicate(_Rule):
    def __init__(self, *a, copies: int = 2):
        super().__init__(*a)
        self.copies = int(copies)

    def act(self, src, dst):
        return Directive(copies=self.copies)


class _Stall(_Rule):
    """Hold matching frames on an Event instead of a wall-clock delay:
    the frame is delivered the instant ``release()`` fires — the
    deterministic slow-node primitive (no sleeps, no timing slop)."""

    def __init__(self, *a):
        super().__init__(*a)
        self.gate = threading.Event()

    def act(self, src, dst):
        return Directive(gate=self.gate)

    def release(self):
        self.gate.set()


class _Partition:
    """Symmetric network split: frames CROSSING the cut (either
    direction) fail fast; traffic within each side flows normally — the
    ``NetworkDisruption.TwoPartitions`` analog (a ``disconnect`` is the
    degenerate one-node-vs-everyone case)."""

    def __init__(self, side_a, side_b):
        self.side_a = frozenset(side_a)
        self.side_b = frozenset(side_b)

    def __call__(self, src: str, dst: str, frame: bytes):
        if (src in self.side_a and dst in self.side_b) \
                or (src in self.side_b and dst in self.side_a):
            raise NodeDisconnectedError(
                f"[fault_injection] partition cut {src}->{dst}")
        return None


class FaultInjector:
    """Installs/uninstalls rules on a ``LocalTransport.Hub``; every
    random draw comes from one seeded stream guarded by a lock, so a
    fixed seed gives a fixed schedule regardless of which fault fires
    first."""

    def __init__(self, hub, seed: int = 0):
        self.hub = hub
        self.seed = int(seed)
        self._rng = random.Random(seed)
        self._rng_lock = threading.Lock()
        self._installed: list = []
        self._partitions: dict[str, object] = {}
        self._group_partitions: list[_Partition] = []

    def _random(self) -> float:
        with self._rng_lock:
            return self._rng.random()

    def _install(self, rule):
        self.hub.add_rule(rule)
        self._installed.append(rule)
        return rule

    # -- faults ------------------------------------------------------------

    def drop(self, action: str = "*", source: Optional[str] = None,
             target: Optional[str] = None, probability: float = 1.0,
             times: Optional[int] = None, silent: bool = False):
        """Drop matching frames.  ``silent=True`` swallows them (the
        sender times out); default raises at send time (the sender sees
        a NodeDisconnectedError immediately)."""
        return self._install(_Drop(self, action, source, target,
                                   probability, times, silent=silent))

    def delay(self, seconds: float, action: str = "*",
              source: Optional[str] = None, target: Optional[str] = None,
              probability: float = 1.0, times: Optional[int] = None):
        return self._install(_Delay(self, action, source, target,
                                    probability, times, seconds=seconds))

    def duplicate(self, action: str = "*", source: Optional[str] = None,
                  target: Optional[str] = None, probability: float = 1.0,
                  times: Optional[int] = None, copies: int = 2):
        """Deliver matching frames ``copies`` times — the at-least-once
        hazard handlers must tolerate (idempotency probes)."""
        return self._install(_Duplicate(self, action, source, target,
                                        probability, times, copies=copies))

    def stall(self, action: str = "*", source: Optional[str] = None,
              target: Optional[str] = None, probability: float = 1.0,
              times: Optional[int] = None) -> _Stall:
        """Hold matching frames until the returned rule's ``release()``
        is called (delivery is event-driven, not timed)."""
        return self._install(_Stall(self, action, source, target,
                                    probability, times))

    def slow_search_node(self, node_id: str, seconds: float,
                         times: Optional[int] = None):
        """Degrade one data node's shard query phase: every
        ``indices:data/read/search[shards]`` frame TO ``node_id`` is
        delayed — the canonical adaptive-replica-selection scenario (the
        coordinator should derank the node and reroute to healthy
        copies)."""
        from opensearch_tpu.cluster.node import A_SEARCH_SHARDS
        return self.delay(seconds, action=A_SEARCH_SHARDS,
                          target=node_id, times=times)

    def induce_search_duress(self, service, ticks: int = 1) -> None:
        """Deterministic duress simulation: force the given
        SearchBackpressureService's next ``ticks`` evaluations to read
        as node-in-duress, bypassing the real probes — the fault
        harness's answer to 'make this node overloaded NOW' without
        burning real CPU or heap."""
        service.force_duress(ticks)

    def disconnect(self, node_id: str):
        """Full partition: everything to/from ``node_id`` fails fast."""
        if node_id in self._partitions:
            return self._partitions[node_id]
        rule = self.hub.disconnect(node_id)
        self._installed.append(rule)
        self._partitions[node_id] = rule
        return rule

    def partition(self, side_a, side_b) -> _Partition:
        """Symmetric split between two node groups: every frame crossing
        the cut fails fast in BOTH directions, while each side keeps
        talking internally (so a minority side can still try — and fail —
        to reach quorum).  Returns the rule; ``heal_partition()`` lifts
        it (or all of them)."""
        rule = _Partition(side_a, side_b)
        self._install(rule)
        self._group_partitions.append(rule)
        return rule

    def heal_partition(self, rule: Optional[_Partition] = None) -> bool:
        """Lift one ``partition()`` (or every installed one)."""
        victims = ([rule] if rule is not None
                   else list(self._group_partitions))
        healed = False
        for r in victims:
            if r in self._group_partitions:
                self._group_partitions.remove(r)
                self._installed.remove(r)
                healed = self.hub.remove_rule(r) or healed
        return healed

    def heal(self, node_id: str) -> bool:
        """Lift a ``disconnect`` partition."""
        rule = self._partitions.pop(node_id, None)
        if rule is None:
            return False
        self._installed.remove(rule)
        return self.hub.remove_rule(rule)

    def remove(self, rule) -> bool:
        if rule in self._installed:
            self._installed.remove(rule)
        for nid, r in list(self._partitions.items()):
            if r is rule:
                del self._partitions[nid]
        if rule in self._group_partitions:
            self._group_partitions.remove(rule)
        return self.hub.remove_rule(rule)

    def clear(self):
        """Uninstall every rule THIS injector added (other hub rules are
        left alone, unlike ``hub.clear_rules``)."""
        for rule in self._installed:
            self.hub.remove_rule(rule)
        self._installed.clear()
        self._partitions.clear()
        self._group_partitions.clear()


# ---------------------------------------------------------------------------
# Disk fault injection (the MockFileSystem / disruptive-FS analog)
# ---------------------------------------------------------------------------


class _DiskRule:
    """One installed disk fault: matches (op, absolute path) by fnmatch
    pattern, ``times``-bounded or sticky, probability drawn from the
    injector's seeded stream — the same Directive idioms as the
    transport rules above."""

    def __init__(self, injector: "DiskFaultInjector", op: str,
                 pattern: str, probability: float, times: Optional[int],
                 **params):
        self.injector = injector
        self.op = op                     # read | write | fsync
        self.pattern = pattern
        self.probability = float(probability)
        self.remaining = times           # None = sticky
        self.params = params
        self._lock = threading.Lock()

    def matches(self, op: str, path: str) -> bool:
        if op != self.op:
            return False
        if path != self.pattern and not fnmatch.fnmatch(path, self.pattern):
            return False
        with self._lock:
            if self.remaining is not None and self.remaining <= 0:
                return False
            if self.probability < 1.0 \
                    and self.injector._random() >= self.probability:
                return False
            if self.remaining is not None:
                self.remaining -= 1
        return True


class _CorruptedReader:
    """File-object proxy serving pre-corrupted bytes; supports the
    read/iterate/context-manager surface the store and json/numpy
    loaders use."""

    def __init__(self, path: str, data: bytes, text: bool):
        import io
        self.name = path
        self._buf = (io.StringIO(data.decode("utf-8", "replace"))
                     if text else io.BytesIO(data))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __iter__(self):
        return iter(self._buf)

    def __getattr__(self, name):
        return getattr(self._buf, name)


class DiskFaultInjector:
    """Deterministic disk-level fault injection: while active, patches
    ``builtins.open`` and ``os.fsync`` so files whose ABSOLUTE PATH
    matches an installed rule misbehave — bit-flips and truncation on
    read, EIO/ENOSPC on write or fsync, slow fsync — everything else
    passes through untouched.  Every probabilistic choice and corruption
    offset comes from one seeded stream, so a fixed seed replays the
    same damage.

    Usage::

        disk = DiskFaultInjector(seed=7)
        disk.corrupt_read(f"{data}/segments/*.npz", times=1)
        disk.fail_fsync(f"{data}/*", err=errno.EIO)
        with disk:                       # activate() / deactivate()
            ...
    """

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self._rng = random.Random(seed)
        self._rng_lock = threading.Lock()
        self._rules: list[_DiskRule] = []
        self._rules_lock = threading.Lock()
        self._active = False
        self._real_open = None
        self._real_fsync = None
        self._fd_paths: dict[int, str] = {}

    def _random(self) -> float:
        with self._rng_lock:
            return self._rng.random()

    def _randrange(self, n: int) -> int:
        with self._rng_lock:
            return self._rng.randrange(n)

    # -- lifecycle ---------------------------------------------------------

    def activate(self) -> "DiskFaultInjector":
        import builtins
        if self._active:
            return self
        self._active = True
        self._real_open = builtins.open
        self._real_fsync = os.fsync
        builtins.open = self._open
        os.fsync = self._fsync
        return self

    def deactivate(self):
        import builtins
        if not self._active:
            return
        builtins.open = self._real_open
        os.fsync = self._real_fsync
        self._active = False
        self._fd_paths.clear()

    __enter__ = activate

    def __exit__(self, *exc):
        self.deactivate()
        return False

    # -- rules -------------------------------------------------------------

    def _install(self, rule: _DiskRule) -> _DiskRule:
        with self._rules_lock:
            self._rules.append(rule)
        return rule

    def corrupt_read(self, pattern: str, times: Optional[int] = None,
                     probability: float = 1.0,
                     mode: str = "bitflip") -> _DiskRule:
        """Serve damaged bytes when a matching file is opened for
        reading: ``bitflip`` XORs one seeded byte, ``truncate`` cuts the
        tail at a seeded offset — the two bit-rot shapes checksum
        verification must catch."""
        if mode not in ("bitflip", "truncate"):
            raise ValueError(f"unknown corruption mode [{mode}]")
        return self._install(_DiskRule(self, "read", pattern, probability,
                                       times, mode=mode))

    def fail_read(self, pattern: str, err: Optional[int] = None,
                  times: Optional[int] = None,
                  probability: float = 1.0) -> _DiskRule:
        """EIO (or ``err``) when a matching file is opened for reading."""
        import errno
        return self._install(_DiskRule(self, "read", pattern, probability,
                                       times, err=err or errno.EIO))

    def fail_write(self, pattern: str, err: Optional[int] = None,
                   times: Optional[int] = None,
                   probability: float = 1.0) -> _DiskRule:
        """EIO (or ``err``) when a matching file is opened for writing."""
        import errno
        return self._install(_DiskRule(self, "write", pattern, probability,
                                       times, err=err or errno.EIO))

    def enospc(self, pattern: str, times: Optional[int] = None,
               probability: float = 1.0) -> _DiskRule:
        """Disk-full on write — the classic slow-death failure mode."""
        import errno
        return self.fail_write(pattern, err=errno.ENOSPC, times=times,
                               probability=probability)

    def fail_fsync(self, pattern: str, err: Optional[int] = None,
                   times: Optional[int] = None,
                   probability: float = 1.0) -> _DiskRule:
        """EIO (or ``err``) from ``os.fsync`` on a matching file — the
        fault FsHealthService's probe exists to notice."""
        import errno
        return self._install(_DiskRule(self, "fsync", pattern, probability,
                                       times, err=err or errno.EIO))

    def slow_fsync(self, pattern: str, seconds: float,
                   times: Optional[int] = None,
                   probability: float = 1.0) -> _DiskRule:
        """Delay ``os.fsync`` on a matching file (degrading-disk shape:
        the write path stalls before it fails)."""
        return self._install(_DiskRule(self, "fsync", pattern, probability,
                                       times, seconds=float(seconds)))

    def remove(self, rule: _DiskRule) -> bool:
        with self._rules_lock:
            if rule in self._rules:
                self._rules.remove(rule)
                return True
        return False

    def clear(self):
        with self._rules_lock:
            self._rules.clear()

    # -- patched entry points ----------------------------------------------

    def _match(self, op: str, path: str) -> Optional[_DiskRule]:
        with self._rules_lock:
            rules = list(self._rules)
        for rule in rules:
            if rule.matches(op, path):
                return rule
        return None

    def _corrupt(self, data: bytes, mode: str) -> bytes:
        if not data:
            return data
        if mode == "truncate":
            return data[: self._randrange(len(data))]
        i = self._randrange(len(data))
        return data[:i] + bytes([data[i] ^ 0xFF]) + data[i + 1:]

    def _open(self, file, mode="r", *args, **kwargs):
        real = self._real_open
        if not isinstance(file, (str, bytes, os.PathLike)):
            return real(file, mode, *args, **kwargs)
        path = os.path.abspath(os.fsdecode(file))
        writing = any(c in mode for c in "wax+")
        rule = self._match("write" if writing else "read", path)
        if rule is not None and "err" in rule.params:
            raise OSError(rule.params["err"],
                          "[fault_injection] injected disk error", path)
        if rule is not None and not writing and "mode" in rule.params:
            with real(path, "rb") as f:
                data = f.read()
            return _CorruptedReader(path, self._corrupt(
                data, rule.params["mode"]), text="b" not in mode)
        f = real(file, mode, *args, **kwargs)
        try:
            self._fd_paths[f.fileno()] = path
        except (OSError, ValueError, AttributeError):
            pass
        return f

    def _fsync(self, fd):
        path = self._fd_paths.get(fd)
        if path is not None:
            rule = self._match("fsync", path)
            if rule is not None:
                if "seconds" in rule.params:
                    time.sleep(rule.params["seconds"])
                else:
                    raise OSError(rule.params["err"],
                                  "[fault_injection] injected fsync error",
                                  path)
        return self._real_fsync(fd)


# ---------------------------------------------------------------------------
# Device fault injection (the accelerator's failure modes)
# ---------------------------------------------------------------------------


class InjectedDeviceError(RuntimeError):
    """Base for injected accelerator faults; ``__device_fault__`` is
    what ``common/device_health.py::is_device_error`` classifies on, so
    the degradation paths treat these exactly like real jax/XLA
    runtime errors."""

    __device_fault__ = True


class InjectedOOMError(InjectedDeviceError):
    """Staging RESOURCE_EXHAUSTED (the device allocator's OOM shape)."""


class InjectedCompileError(InjectedDeviceError):
    """XLA compilation failure at dispatch time."""


class InjectedDispatchError(InjectedDeviceError):
    """A launched device program failing mid-execution."""


class InjectedMeshLossError(InjectedDeviceError):
    """A mesh member dropping out of the device collective."""


class _DeviceRule:
    """One installed device fault: matches (op, names...) by fnmatch
    pattern against any of the site's name candidates (kernel name,
    segment id, staging kind), ``times``-bounded or sticky, probability
    drawn from the injector's seeded stream — the same Directive idioms
    as the transport and disk rules above."""

    def __init__(self, injector: "DeviceFaultInjector", op: str,
                 pattern: str, probability: float, times: Optional[int],
                 **params):
        self.injector = injector
        self.op = op               # stage | dispatch | mesh
        self.pattern = pattern
        self.probability = float(probability)
        self.remaining = times     # None = sticky
        self.params = params
        self.fired = 0
        self._lock = threading.Lock()

    def matches(self, op: str, names: tuple) -> bool:
        if op != self.op:
            return False
        if self.pattern not in ("*", None):
            for name in names:
                # exact first (fnmatch metachars can appear in segment
                # ids), then glob
                if name == self.pattern \
                        or fnmatch.fnmatch(str(name), self.pattern):
                    break
            else:
                return False
        with self._lock:
            if self.remaining is not None and self.remaining <= 0:
                return False
            if self.probability < 1.0 \
                    and self.injector._random() >= self.probability:
                return False
            if self.remaining is not None:
                self.remaining -= 1
            self.fired += 1
        return True


class DeviceFaultInjector:
    """Deterministic accelerator fault injection: while active, wraps
    the sanctioned device entry points — the residency ledger's
    ``stage``/``device_put`` (every H2D transfer flows through them,
    enforced by tools/check_device_staging.py), the query-path kernels
    ``plan.run_topk``/``plan.run_full``, the batched kernel
    ``batch.batch_impact_union_topk``, and the mesh collective
    ``MeshSearcher.search``/``mesh_metric_aggs`` — so matching calls
    misbehave: staging RESOURCE_EXHAUSTED, XLA compile failure,
    dispatch exceptions, slow-device latency, NaN-poisoned top-k
    scores, mesh-member loss.  One-shot or sticky, matched by kernel /
    segment / staging-kind pattern; every probabilistic choice comes
    from one seeded stream, so a fixed seed replays the same faults.

    Usage::

        dev = DeviceFaultInjector(seed=7)
        dev.oom("seg_*")                 # sticky staging OOM
        dev.poison_topk(times=3)         # 3 NaN-poisoned results
        dev.slow_device(0.05, times=2)
        dev.lose_mesh_member()
        with dev:                        # activate() / deactivate()
            ...
    """

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self._rng = random.Random(seed)
        self._rng_lock = threading.Lock()
        self._rules: list[_DeviceRule] = []
        self._rules_lock = threading.Lock()
        self._active = False
        self._saved: list[tuple] = []

    def _random(self) -> float:
        with self._rng_lock:
            return self._rng.random()

    # -- lifecycle ---------------------------------------------------------

    def activate(self) -> "DeviceFaultInjector":
        if self._active:
            return self
        self._active = True
        from opensearch_tpu.common.device_ledger import device_ledger
        from opensearch_tpu.parallel import dist_search
        from opensearch_tpu.search import batch as batch_mod
        from opensearch_tpu.search import plan as plan_mod

        led = device_ledger()
        inj = self

        real_stage = led.stage

        def stage(group, host_array, *, kind: str, field: str = "",
                  name: str = ""):
            seg = getattr(group, "segment", "-") if group is not None \
                else "-"
            inj._check("stage", (seg, kind, field))
            return real_stage(group, host_array, kind=kind, field=field,
                              name=name)

        real_put = led.device_put

        def device_put(group, value, sharding=None, *, kind: str = "mesh",
                       field: str = "", name: str = ""):
            seg = getattr(group, "segment", "-") if group is not None \
                else "-"
            inj._check("stage", (seg, kind, name))
            return real_put(group, value, sharding, kind=kind,
                            field=field, name=name)

        self._saved.append((led, "stage", led.__dict__.get("stage")))
        self._saved.append((led, "device_put",
                            led.__dict__.get("device_put")))
        led.stage = stage
        led.device_put = device_put

        def wrap_kernel(mod, attr):
            real = getattr(mod, attr)

            def kernel(*args, **kwargs):
                inj._check("dispatch", (attr,))
                out = real(*args, **kwargs)
                return inj._maybe_poison(attr, out)
            self._saved.append((mod, attr, real))
            setattr(mod, attr, kernel)

        wrap_kernel(plan_mod, "run_topk")
        wrap_kernel(plan_mod, "run_full")
        wrap_kernel(batch_mod, "batch_impact_union_topk")

        def wrap_mesh(attr):
            real = getattr(dist_search.MeshSearcher, attr)

            def mesh_entry(ms_self, *args, **kwargs):
                inj._check("mesh", (attr,))
                return real(ms_self, *args, **kwargs)
            self._saved.append((dist_search.MeshSearcher, attr, real))
            setattr(dist_search.MeshSearcher, attr, mesh_entry)

        wrap_mesh("search")
        wrap_mesh("mesh_metric_aggs")
        return self

    def deactivate(self):
        if not self._active:
            return
        for owner, attr, prev in reversed(self._saved):
            if isinstance(owner, type) or hasattr(owner, "__name__"):
                setattr(owner, attr, prev)
            elif prev is None:
                owner.__dict__.pop(attr, None)   # restore the bound method
            else:
                setattr(owner, attr, prev)
        self._saved.clear()
        self._active = False

    __enter__ = activate

    def __exit__(self, *exc):
        self.deactivate()
        return False

    # -- the interception core ---------------------------------------------

    def _match(self, op: str, names: tuple) -> Optional[_DeviceRule]:
        with self._rules_lock:
            rules = list(self._rules)
        for rule in rules:
            if rule.matches(op, names):
                return rule
        return None

    def _check(self, op: str, names: tuple) -> None:
        rule = self._match(op, names)
        if rule is None:
            return
        if "seconds" in rule.params:
            time.sleep(rule.params["seconds"])
            return
        err = rule.params.get("err")
        if err is not None:
            raise err(rule.params["message"].format(names=names))

    def _maybe_poison(self, kernel: str, out):
        """NaN-poison the score component of a top-k kernel result (the
        first array of the tuple) — the silent-corruption failure shape
        the result-sanity guard exists to catch."""
        rule = self._match("poison", (kernel,))
        if rule is None:
            return out
        import jax.numpy as jnp
        vals = out[0]
        return (jnp.full_like(vals, jnp.nan), *out[1:])

    # -- rules -------------------------------------------------------------

    def _install(self, rule: _DeviceRule) -> _DeviceRule:
        with self._rules_lock:
            self._rules.append(rule)
        return rule

    def oom(self, pattern: str = "*", times: Optional[int] = None,
            probability: float = 1.0) -> _DeviceRule:
        """RESOURCE_EXHAUSTED on matching H2D stagings (pattern matches
        segment id, staging kind, or field)."""
        return self._install(_DeviceRule(
            self, "stage", pattern, probability, times,
            err=InjectedOOMError,
            message="RESOURCE_EXHAUSTED: out of memory while staging "
                    "{names} (injected)"))

    def compile_failure(self, pattern: str = "*",
                        times: Optional[int] = None,
                        probability: float = 1.0) -> _DeviceRule:
        """XLA compile failure on matching kernel dispatches."""
        return self._install(_DeviceRule(
            self, "dispatch", pattern, probability, times,
            err=InjectedCompileError,
            message="INTERNAL: XLA compilation of {names} failed "
                    "(injected)"))

    def dispatch_error(self, pattern: str = "*",
                       times: Optional[int] = None,
                       probability: float = 1.0) -> _DeviceRule:
        """A matching device program fails at launch."""
        return self._install(_DeviceRule(
            self, "dispatch", pattern, probability, times,
            err=InjectedDispatchError,
            message="INTERNAL: device program {names} failed "
                    "(injected)"))

    def slow_device(self, seconds: float, pattern: str = "*",
                    times: Optional[int] = None,
                    probability: float = 1.0) -> _DeviceRule:
        """Matching dispatches stall ``seconds`` before launching (the
        degrading-accelerator latency shape)."""
        return self._install(_DeviceRule(
            self, "dispatch", pattern, probability, times,
            seconds=float(seconds)))

    def poison_topk(self, pattern: str = "*",
                    times: Optional[int] = None,
                    probability: float = 1.0) -> _DeviceRule:
        """Matching top-k kernels return NaN scores instead of real
        ones — caught by the result-sanity guard at the D2H sync, which
        discards and recomputes on the host."""
        return self._install(_DeviceRule(
            self, "poison", pattern, probability, times))

    def lose_mesh_member(self, times: Optional[int] = None,
                         probability: float = 1.0) -> _DeviceRule:
        """The mesh collective loses a member mid-dispatch; the engine
        must demote to the counted host scatter fallback."""
        return self._install(_DeviceRule(
            self, "mesh", "*", probability, times,
            err=InjectedMeshLossError,
            message="UNAVAILABLE: mesh member lost during {names} "
                    "(injected)"))

    def remove(self, rule: _DeviceRule) -> bool:
        with self._rules_lock:
            if rule in self._rules:
                self._rules.remove(rule)
                return True
        return False

    def clear(self):
        with self._rules_lock:
            self._rules.clear()

    def stats(self) -> dict:
        with self._rules_lock:
            return {"rules": len(self._rules),
                    "fired": sum(r.fired for r in self._rules)}


# ---------------------------------------------------------------------------
# Remote blob-store fault injection (the search tier's "S3 is down")
# ---------------------------------------------------------------------------


class RemoteStoreFaultInjector:
    """Deterministic remote-store outage: while active, the given
    repositories' blob reads (searcher pulls) and/or writes (primary
    uploads) raise ``RemoteStoreError`` — the blob-service-outage class
    of fault the transport/disk injectors cannot reach, because the
    store is accessed as a library, not over the cluster transport.

    Each cluster node holds its OWN ``Repository`` object over the
    shared location (every reference node names the same bucket), so
    the injector patches the bound ``read_blob``/``write_blob`` of
    every repo it is given.  Soak's ``stall_remote_store`` directive
    stalls reads fleet-wide; ``release_remote_store`` restores."""

    def __init__(self, repos):
        self._repos = list(repos)
        self._saved: list[tuple] = []
        self.failed_reads = 0
        self.failed_writes = 0
        self._lock = threading.Lock()

    def stall(self, reads: bool = True, writes: bool = False) -> None:
        from opensearch_tpu.index.remote_store import RemoteStoreError
        if self._saved:
            return                       # already active
        for repo in self._repos:
            blobs = repo.blobs
            self._saved.append(
                (blobs, blobs.read_blob, blobs.write_blob))
            if reads:
                def failing_read(name, _inj=self, _repo=repo):
                    with _inj._lock:
                        _inj.failed_reads += 1
                    raise RemoteStoreError(
                        "[fault_injection] remote store stalled "
                        f"(read of [{name}])")
                blobs.read_blob = failing_read
            if writes:
                def failing_write(name, data, fail_if_exists=False,
                                  _inj=self):
                    with _inj._lock:
                        _inj.failed_writes += 1
                    raise RemoteStoreError(
                        "[fault_injection] remote store stalled "
                        f"(write of [{name}])")
                blobs.write_blob = failing_write

    def release(self) -> None:
        for blobs, read, write in self._saved:
            blobs.read_blob = read
            blobs.write_blob = write
        self._saved.clear()

    def __enter__(self):
        self.stall()
        return self

    def __exit__(self, *exc):
        self.release()
