"""Cross-shard search over a device mesh: the scatter-gather phase as XLA
collectives.

Analog of the reference's coordinator fan-out + reduce
(action/search/AbstractSearchAsyncAction.java:223 run/performPhaseOnShard,
SearchPhaseController.sortDocs:175 merge) — but where the reference sends
per-shard RPCs and heap-merges topdocs on one coordinator node, here every
shard is a mesh device, scoring runs data-parallel on all shards at once,
and the merge is an ``all_gather`` of each shard's local top-k followed by
a redundant on-device re-top-k (riding ICI, no host round-trip).

Search-engine parallelism axes (SURVEY §2.3): corpus sharding == data
parallelism over docs ("shards" mesh axis); replica groups for read
throughput would be an outer mesh axis whose devices hold identical arrays
— no TP/PP analog exists because scoring is embarrassingly parallel over
docs.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import opensearch_tpu.common.jaxenv  # noqa: F401
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from opensearch_tpu.ops import bm25 as bm25_ops


def make_mesh(n_devices: int, axis: str = "shards") -> Mesh:
    devs = jax.devices()[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def stack_shards(shard_list: list[dict]) -> dict:
    """Stack per-shard array dicts (identical bucketed shapes) along a new
    leading 'shards' axis, ready to place on the mesh."""
    out = {}
    for key in shard_list[0]:
        out[key] = np.stack([np.asarray(s[key]) for s in shard_list])
    return out


def put_on_mesh(stacked: dict, mesh: Mesh, axis: str = "shards") -> dict:
    sharding = NamedSharding(mesh, P(axis))
    return {k: jax.device_put(v, sharding) for k, v in stacked.items()}


def prepare_match_query(segments: list, field: str, terms: list[str]):
    """Host-side prep: per-shard postings staged to COMMON bucketed shapes
    + per-shard term ids + GLOBAL collection stats (idf/avgdl summed over
    shards, so sharded scores match single-shard scores exactly — the
    DFS_QUERY_THEN_FETCH global-stats guarantee, ref search/dfs/DfsPhase.java).

    Returns (stacked dict [S, ...], meta dict with n_pad/budget/k-free dims).
    """
    from opensearch_tpu.index.segment import pad_pow2

    n_pad = pad_pow2(max(s.n_docs for s in segments) + 1)
    t_pad = pad_pow2(max((len(s.postings[field].offsets) for s in segments
                          if field in s.postings), default=8))
    p_pad = pad_pow2(max((len(s.postings[field].doc_ids) for s in segments
                          if field in s.postings), default=8))
    q_pad = pad_pow2(len(terms))

    doc_count = sum(s.postings[field].docs_with_field
                    for s in segments if field in s.postings)
    total_len = sum(s.postings[field].total_len
                    for s in segments if field in s.postings)
    avgdl = total_len / doc_count if doc_count else 1.0
    dfs = []
    for t in terms:
        df = 0
        for s in segments:
            pf = s.postings.get(field)
            if pf is not None:
                tid = pf.term_id(t)
                if tid >= 0:
                    df += int(pf.df[tid])
        dfs.append(df)
    idfs = np.zeros(q_pad, np.float32)
    for i, df in enumerate(dfs):
        idfs[i] = bm25_ops.idf(df, doc_count)

    shards = []
    budget = 8
    for s in segments:
        pf = s.postings.get(field)
        sh = {
            "offsets": np.zeros(t_pad, np.int32),
            "doc_ids": np.full(p_pad, n_pad - 1, np.int32),
            "tfs": np.zeros(p_pad, np.float32),
            "doc_lens": np.ones(n_pad, np.float32),
            "tids": np.zeros(q_pad, np.int32),
            "active": np.zeros(q_pad, bool),
            "idfs": idfs,
            "weights": np.where(np.arange(q_pad) < len(terms), 1.0, 0.0
                                ).astype(np.float32),
            "avgdl": np.float32(avgdl),
        }
        if pf is not None:
            sh["offsets"][: len(pf.offsets)] = pf.offsets
            sh["offsets"][len(pf.offsets):] = pf.offsets[-1]
            sh["doc_ids"][: len(pf.doc_ids)] = pf.doc_ids
            sh["tfs"][: len(pf.tfs)] = pf.tfs
            sh["doc_lens"][: len(pf.doc_lens)] = pf.doc_lens
            local_budget = 0
            for i, t in enumerate(terms):
                tid = pf.term_id(t)
                if tid >= 0:
                    sh["tids"][i] = tid
                    sh["active"][i] = True
                    local_budget += int(pf.df[tid])
            budget = max(budget, pad_pow2(local_budget))
        shards.append(sh)
    return stack_shards(shards), {"n_pad": n_pad, "budget": budget}


def sharded_bm25_topk(mesh: Mesh, *, n_pad: int, budget: int, k: int,
                      axis: str = "shards"):
    """Build the jitted one-step distributed query: every device scores its
    own shard's postings block and the global top-k is reduced with an
    all-gather over the mesh axis.

    Inputs (per call): shard-stacked arrays [S, ...] for offsets/doc_ids/
    tfs/doc_lens/term_ids/active/idfs and scalars replicated [S] for
    avgdl.  Returns (scores[k], global_doc_ids[k]) replicated on all
    devices; global doc id = shard * n_pad + local id, so ties break by
    (score desc, shard asc, local doc asc) — the coordinator merge order.
    """

    def local_step(offsets, doc_ids, tfs, doc_lens, tids, active, idfs,
                   weights, avgdl):
        # shard_map hands each device a [1, ...] block — drop the axis
        scores, _count = bm25_ops.bm25_score_count(
            offsets[0], doc_ids[0], tfs[0], doc_lens[0], tids[0], active[0],
            idfs[0], weights[0], avgdl[0],
            n_pad=n_pad, budget=budget, scored=True)
        vals, idx = lax.top_k(scores, k)
        shard = lax.axis_index(axis)
        gids = shard.astype(jnp.int64) * n_pad + idx
        all_vals = lax.all_gather(vals, axis)     # [S, k] on every device
        all_gids = lax.all_gather(gids, axis)
        fv, fi = lax.top_k(all_vals.reshape(-1), k)
        return fv, all_gids.reshape(-1)[fi]

    spec = P(axis)
    # check_vma=False: the outputs ARE replicated (all_gather + identical
    # re-top-k on every device) but the varying-mesh-axes checker cannot
    # infer that statically.
    fn = shard_map(local_step, mesh=mesh,
                   in_specs=(spec,) * 9,
                   out_specs=(P(), P()),
                   check_vma=False)
    return jax.jit(fn)
