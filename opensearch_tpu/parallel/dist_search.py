"""Cross-shard search over a device mesh: the scatter-gather phase as XLA
collectives.

Analog of the reference's coordinator fan-out + reduce
(action/search/AbstractSearchAsyncAction.java:223 run/performPhaseOnShard,
SearchPhaseController.sortDocs:175 merge) — but where the reference sends
per-shard RPCs and heap-merges topdocs on one coordinator node, here every
shard is a mesh device, scoring runs data-parallel on all shards at once,
and the merge is an ``all_gather`` of each shard's local top-k followed by
a redundant on-device re-top-k (riding ICI, no host round-trip).

Search-engine parallelism axes (SURVEY §2.3): corpus sharding == data
parallelism over docs ("shards" mesh axis); replica groups for read
throughput would be an outer mesh axis whose devices hold identical arrays
— no TP/PP analog exists because scoring is embarrassingly parallel over
docs.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import numpy as np

import opensearch_tpu.common.jaxenv  # noqa: F401
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# jax moved shard_map out of experimental (and renamed the replication
# checker kwarg check_rep -> check_vma) across the versions this engine
# supports; normalize on one callable so the mesh path works on both.
# When neither spelling exists the mesh is unavailable and
# IndexService._mesh_search degrades to the host scatter path (counted
# in search.mesh.fallback) instead of crashing the request.
try:
    from jax import shard_map as _shard_map_impl
    _CHECK_KW = "check_vma"
except ImportError:                    # pre-0.6 jax: experimental module
    try:
        from jax.experimental.shard_map import shard_map as _shard_map_impl
        _CHECK_KW = "check_rep"
    except ImportError:
        _shard_map_impl = None
        _CHECK_KW = None

MESH_AVAILABLE = _shard_map_impl is not None


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    if _shard_map_impl is None:
        raise ImportError("no shard_map in this jax installation")
    kw = {_CHECK_KW: check_vma}
    return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, **kw)


from opensearch_tpu.ops import bm25 as bm25_ops   # noqa: E402


def make_mesh(n_devices: int, axis: str = "shards") -> Mesh:
    devs = jax.devices()[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def stack_shards(shard_list: list[dict]) -> dict:
    """Stack per-shard array dicts (identical bucketed shapes) along a new
    leading 'shards' axis, ready to place on the mesh."""
    out = {}
    for key in shard_list[0]:
        out[key] = np.stack([np.asarray(s[key]) for s in shard_list])
    return out


def put_on_mesh(stacked: dict, mesh: Mesh, axis: str = "shards") -> dict:
    """Place shard-stacked host arrays on the mesh.  Routed through the
    device ledger so the H2D transfer is byte-accounted (these are
    per-query inputs, not resident state — the resident mesh copies are
    the DeviceSegments MeshSearcher stages per device)."""
    from opensearch_tpu.common.device_ledger import device_ledger

    led = device_ledger()
    sharding = NamedSharding(mesh, P(axis))
    return {k: led.device_put(None, v, sharding, kind="mesh", name=k)
            for k, v in stacked.items()}


def prepare_match_query(segments: list, field: str, terms: list[str]):
    """Host-side prep: per-shard postings staged to COMMON bucketed shapes
    + per-shard term ids + GLOBAL collection stats (idf/avgdl summed over
    shards, so sharded scores match single-shard scores exactly — the
    DFS_QUERY_THEN_FETCH global-stats guarantee, ref search/dfs/DfsPhase.java).

    Ported onto the PR-5 eager impact tables (ROADMAP item 1's mesh
    leftover): instead of staging raw tfs + doc_lens and recomputing the
    BM25 norm per query on every device, each shard stages its
    PRECOMPUTED per-posting impact column (``Segment.impact_table`` at
    the GLOBAL avgdl — bit-identical to what the host fast path and the
    device kernels read), so the mesh query degenerates to the same
    gather + idf-weighted scatter the unified engine lowers everywhere
    else.  Byte-parity with the host path is pinned in
    tests/test_dist_search.py.

    Returns (stacked dict [S, ...], meta dict with n_pad/budget/k-free dims).
    """
    from opensearch_tpu.index.segment import pad_pow2

    n_pad = pad_pow2(max(s.n_docs for s in segments) + 1)
    t_pad = pad_pow2(max((len(s.postings[field].offsets) for s in segments
                          if field in s.postings), default=8))
    p_pad = pad_pow2(max((len(s.postings[field].doc_ids) for s in segments
                          if field in s.postings), default=8))
    q_pad = pad_pow2(len(terms))

    doc_count = sum(s.postings[field].docs_with_field
                    for s in segments if field in s.postings)
    total_len = sum(s.postings[field].total_len
                    for s in segments if field in s.postings)
    avgdl = total_len / doc_count if doc_count else 1.0
    dfs = []
    for t in terms:
        df = 0
        for s in segments:
            pf = s.postings.get(field)
            if pf is not None:
                tid = pf.term_id(t)
                if tid >= 0:
                    df += int(pf.df[tid])
        dfs.append(df)
    idfs = np.zeros(q_pad, np.float32)
    for i, df in enumerate(dfs):
        idfs[i] = bm25_ops.idf(df, doc_count)

    shards = []
    budget = 8
    for s in segments:
        pf = s.postings.get(field)
        sh = {
            "offsets": np.zeros(t_pad, np.int32),
            "doc_ids": np.full(p_pad, n_pad - 1, np.int32),
            "impacts": np.zeros(p_pad, np.float32),
            "tids": np.zeros(q_pad, np.int32),
            "active": np.zeros(q_pad, bool),
            "idfs": idfs,
            "weights": np.where(np.arange(q_pad) < len(terms), 1.0, 0.0
                                ).astype(np.float32),
        }
        if pf is not None:
            # the shard's eager impact table at the GLOBAL avgdl: no
            # per-query norm math ever reaches the mesh kernel
            impacts, _mx = s.impact_table(field, avgdl)
            sh["offsets"][: len(pf.offsets)] = pf.offsets
            sh["offsets"][len(pf.offsets):] = pf.offsets[-1]
            sh["doc_ids"][: len(pf.doc_ids)] = pf.doc_ids
            sh["impacts"][: len(impacts)] = impacts
            local_budget = 0
            for i, t in enumerate(terms):
                tid = pf.term_id(t)
                if tid >= 0:
                    sh["tids"][i] = tid
                    sh["active"][i] = True
                    local_budget += int(pf.df[tid])
            budget = max(budget, pad_pow2(local_budget))
        shards.append(sh)
    return stack_shards(shards), {"n_pad": n_pad, "budget": budget}


def sharded_topk_merge(mesh: Mesh, k: int, axis: str = "shards"):
    """The coordinator reduce as an ICI collective: every device holds its
    shard's local top-k (vals[k] desc, rows already tie-broken locally);
    all-gather + redundant re-top-k yields the global top-k replicated on
    every device — replacing SearchPhaseController.sortDocs:175's host
    heap merge.  Returns (vals[k], flat_idx[k]) where flat_idx indexes the
    shard-major [S*k] concatenation (shard = flat_idx // k), so ties break
    (score desc, shard asc, local rank asc) exactly like the host merge."""

    def local(vals):
        av = lax.all_gather(vals[0], axis)          # [S, k] on every device
        fv, fi = lax.top_k(av.reshape(-1), k)
        return fv, fi

    return jax.jit(shard_map(local, mesh=mesh, in_specs=(P(axis),),
                             out_specs=(P(), P()), check_vma=False))


class MeshSearcher:
    """Distributed search over shards resident on a device mesh: ANY
    compiled plan (bool/range/match/phrase/knn/...) runs per shard on that
    shard's own device, and the cross-shard top-k merge is an all-gather
    collective riding ICI — the device-resident scatter-gather of SURVEY
    §2.3 (scoring stats are per-shard, like the reference's default
    query_then_fetch).

    One mesh device per shard; shards may have heterogeneous sizes and
    segment counts (each compiles its own bucketed program) — only the
    [S, k] merge is a single SPMD program.
    """

    def __init__(self, shard_searchers: list, mesh: Optional[Mesh] = None,
                 axis: str = "shards"):
        self.shards = shard_searchers
        self.axis = axis
        self.mesh = mesh if mesh is not None else make_mesh(
            len(shard_searchers), axis)
        self.devices = list(self.mesh.devices.flat)
        if len(self.devices) < len(self.shards):
            raise ValueError(
                f"mesh has {len(self.devices)} devices for "
                f"{len(self.shards)} shards")
        # bounded-cache: one compiled merge program per distinct k
        self._merge_cache: dict[int, object] = {}
        # per-(device, segment) staging cache (seg.device() would pin to
        # the default device; mesh copies are staged per device) — kept
        # across refreshes, pruned in update_shards
        self._dsegs: dict = {}

    def update_shards(self, shard_searchers: list):
        """Swap in fresh per-shard searcher snapshots (after a refresh),
        keeping the device staging and compiled-merge caches — only
        segments that no longer exist anywhere are dropped."""
        if len(shard_searchers) > len(self.devices):
            raise ValueError(
                f"mesh has {len(self.devices)} devices for "
                f"{len(shard_searchers)} shards")
        self.shards = shard_searchers
        alive = {seg.seg_id for s in shard_searchers for seg in s.segments}
        self._dsegs = {key: d for key, d in self._dsegs.items()
                       if key[1] in alive}

    def _dseg(self, shard_i: int, seg):
        from opensearch_tpu.index.segment import DeviceSegment

        d = self._dsegs.get((shard_i, seg.seg_id))
        if d is None:
            with jax.default_device(self.devices[shard_i]):
                d = DeviceSegment(seg)
            self._dsegs[(shard_i, seg.seg_id)] = d
        return d

    def supports_mesh_aggs(self, aggs_json: dict) -> bool:
        """True when every agg is a single-level numeric metric over a
        NUMERIC field — the family the ICI partial-reduce covers
        (sum/avg/min/max/value_count/stats); keyword value_count and
        friends stay on the host's ordinal path."""
        if not self.shards:
            return False
        ctx = self.shards[0].ctx
        for body in (aggs_json or {}).values():
            if not isinstance(body, dict):
                return False
            types = [k for k in body if k not in ("aggs", "aggregations",
                                                  "meta")]
            if (len(types) != 1 or types[0] not in _MESH_METRICS
                    or body.get("aggs") or body.get("aggregations")
                    or not isinstance(body[types[0]], dict)):
                return False
            field = body[types[0]].get("field")
            if not field:
                return False
            ft = ctx.field_type(field)
            if ft is None or ft.dv_kind not in ("long", "double"):
                return False
        return True

    def mesh_metric_aggs(self, body: dict, aggs_json: dict) -> dict:
        """size:0 metric-agg request fully on the mesh: every shard
        computes its (sum, count, min, max) partial on its own device,
        ONE collective reduces them over ICI, and the host reads back
        5 scalars per agg — no per-shard partial serialization
        (VERDICT r4 weak #5: the agg reduce as a collective)."""
        import time as _time

        from opensearch_tpu.ops import aggs as agg_ops
        from opensearch_tpu.search.aggs import _finish_metric, parse_aggs
        from opensearch_tpu.search.compiler import compile_query
        from opensearch_tpu.search.executor import build_arrays
        from opensearch_tpu.search.query_dsl import parse_query
        from opensearch_tpu.search import plan as planmod

        t0 = _time.monotonic()
        reqs = parse_aggs(aggs_json)
        q = parse_query(body.get("query"))
        S = len(self.shards)
        neg_inf = jnp.asarray(np.float32(-np.inf))  # staging-ok: scalar
        # phase 1: per-shard on-device partials, async-dispatched
        per_agg_parts: dict[str, list] = {r.name: [] for r in reqs}
        for si, shard in enumerate(self.shards):
            dev = self.devices[si]
            with jax.default_device(dev):
                partial_rows = {r.name: [] for r in reqs}
                total = jnp.float64(0)
                if shard.segments:
                    plan, bind = compile_query(q, shard.ctx, scored=False)
                    needed = plan.arrays()
                    for seg in shard.segments:
                        dseg = self._dseg(si, seg)
                        A = build_arrays(dseg, needed, shard.mapper,
                                         live=shard.ctx.live_jnp(seg,
                                                                 dseg))
                        dims, ins = plan.prepare(bind, seg, dseg,
                                                 shard.ctx)
                        _sc, matched = planmod.run_full(plan, dims, A,
                                                        ins, neg_inf)
                        total = total + matched.sum().astype(jnp.float64)
                        for r in reqs:
                            col = dseg.numeric.get(r.params["field"])
                            if col is None:
                                continue
                            s_, c_, mn_, mx_ = agg_ops.masked_metrics(
                                col["values"], col["value_docs"], matched)
                            partial_rows[r.name].append(
                                (s_, c_, mn_, mx_))
                for r in reqs:
                    rows = partial_rows[r.name]
                    if rows:
                        s_ = sum(x[0] for x in rows)
                        c_ = sum(x[1] for x in rows)
                        mn_ = jnp.min(jnp.stack([x[2] for x in rows]))
                        mx_ = jnp.max(jnp.stack([x[3] for x in rows]))
                    else:
                        s_, c_ = jnp.float64(0), jnp.float64(0)
                        mn_ = jnp.float64(np.inf)
                        mx_ = jnp.float64(-np.inf)
                    # float64 partials: epoch-millis longs and >2^24
                    # counts must survive the collective bit-exact
                    per_agg_parts[r.name].append(jnp.stack(
                        [jnp.asarray(s_, jnp.float64),   # staging-ok: on-device scalars
                         jnp.asarray(c_, jnp.float64),   # staging-ok: on-device scalars
                         jnp.asarray(mn_, jnp.float64),  # staging-ok: on-device scalars
                         jnp.asarray(mx_, jnp.float64),  # staging-ok: on-device scalars
                         total]).reshape(1, 5))
        # phase 2: ONE collective per agg over ICI
        sharding = NamedSharding(self.mesh, P(self.axis))
        reduce = self._merge_cache.get("metric_reduce")
        if reduce is None:
            reduce = sharded_metric_reduce(self.mesh, self.axis)
            self._merge_cache["metric_reduce"] = reduce
        out_aggs = {}
        total_docs = 0
        for r in reqs:
            parts = jax.make_array_from_single_device_arrays(
                (S, 5), sharding, per_agg_parts[r.name])
            merged = np.asarray(reduce(parts))
            s_, c_, mn_, mx_, tot = merged
            total_docs = int(tot)
            out_aggs[r.name] = _finish_metric(
                r.type, (float(s_), int(c_),
                         float(mn_) if c_ else np.inf,
                         float(mx_) if c_ else -np.inf))
        return {
            "took": int((_time.monotonic() - t0) * 1000),
            "timed_out": False,
            "_shards": {"total": S, "successful": S, "skipped": 0,
                        "failed": 0},
            "hits": {"total": {"value": total_docs, "relation": "eq"},
                     "max_score": None, "hits": []},
            "aggregations": out_aggs,
        }

    def search(self, body: Optional[dict] = None) -> dict:
        """Scored top-k search (sort/aggs stay on the host path)."""
        import time as _time

        from opensearch_tpu.search.compiler import compile_query
        from opensearch_tpu.search.executor import build_arrays
        from opensearch_tpu.search.fetch import filter_source
        from opensearch_tpu.search.query_dsl import parse_query
        from opensearch_tpu.search import plan as planmod

        body = body or {}
        t0 = _time.monotonic()
        size = int(body.get("size", 10))
        from_ = int(body.get("from", 0))
        k = max(from_ + size, 1)
        q = parse_query(body.get("query"))
        min_score = body.get("min_score")
        ms = np.float32(-np.inf if min_score is None else min_score)

        S = len(self.shards)
        # Phase 1: DISPATCH every shard's program to its device, keeping
        # only jnp handles — no host sync inside the loop, so the S
        # devices execute concurrently (jax async dispatch).
        shard_vals, shard_rows, totals = [], [], []
        for si, shard in enumerate(self.shards):
            dev = self.devices[si]
            with jax.default_device(dev):
                if not shard.segments:
                    shard_vals.append(
                        jnp.full((1, k), -jnp.inf, jnp.float32))
                    shard_rows.append((jnp.zeros(k, jnp.int32),
                                       jnp.zeros(k, jnp.int32)))
                    totals.append(jnp.int32(0))
                    continue
                plan, bind = compile_query(q, shard.ctx, scored=True)
                needed = plan.arrays()
                seg_vals, seg_ids, seg_locals = [], [], []
                total = jnp.int32(0)
                for gi, seg in enumerate(shard.segments):
                    dseg = self._dseg(si, seg)
                    A = build_arrays(dseg, needed, shard.mapper,
                                     live=shard.ctx.live_jnp(seg, dseg))
                    dims, ins = plan.prepare(bind, seg, dseg, shard.ctx)
                    kk = min(k, dseg.n_pad)
                    vals, idx, tot, _mx = planmod.run_topk(plan, dims, kk,
                                                           A, ins, ms)
                    if kk < k:                       # pad to common k
                        pad = k - kk
                        vals = jnp.concatenate(
                            [vals, jnp.full(pad, -jnp.inf, vals.dtype)])
                        idx = jnp.concatenate(
                            [idx, jnp.zeros(pad, idx.dtype)])
                    seg_vals.append(vals)
                    seg_ids.append(jnp.full(k, gi, jnp.int32))
                    seg_locals.append(idx)
                    total = total + tot
                # shard-local merge of per-segment top-k: flat concat is
                # segment-major, so top_k's lowest-index tie-break
                # reproduces the (score desc, seg asc, doc asc) Lucene
                # merge order
                cat_v = jnp.concatenate(seg_vals)
                row_v, pick = lax.top_k(cat_v, k)
                row_s = jnp.concatenate(seg_ids)[pick]
                row_l = jnp.concatenate(seg_locals)[pick]
                shard_vals.append(row_v.reshape(1, k))
                shard_rows.append((row_s, row_l))
                totals.append(total)

        # Phase 2: device-collective merge over the mesh (the flagship
        # reduce riding ICI)
        sharding = NamedSharding(self.mesh, P(self.axis))
        vals_g = jax.make_array_from_single_device_arrays(
            (S, k), sharding, shard_vals)
        merge = self._merge_cache.get(k)
        if merge is None:
            merge = sharded_topk_merge(self.mesh, k, self.axis)
            self._merge_cache[k] = merge
        fv, fi = merge(vals_g)

        # Phase 3: host-side fetch of the k winners (first host sync)
        from opensearch_tpu.common.device_ledger import device_ledger
        t_sync = _time.monotonic()
        fv = np.asarray(fv)
        fi = np.asarray(fi)
        rows_np = [(np.asarray(s_), np.asarray(l_))
                   for s_, l_ in shard_rows]
        total = int(sum(int(t) for t in totals))
        device_ledger().record_fetch(
            fv.nbytes + fi.nbytes
            + sum(s_.nbytes + l_.nbytes for s_, l_ in rows_np),
            _time.monotonic() - t_sync)

        hits = []
        source_spec = body.get("_source")
        max_score = None
        if size > 0 or from_ > 0:
            for val, flat in zip(fv, fi):
                if val == -np.inf:
                    break
                shard_i, pos = divmod(int(flat), k)
                seg_i = int(rows_np[shard_i][0][pos])
                local = int(rows_np[shard_i][1][pos])
                shard = self.shards[shard_i]
                seg = shard.segments[seg_i]
                hit = {"_index": shard.index_name,
                       "_id": seg.doc_ids[local],
                       "_score": float(val), "_shard": shard.shard_id}
                src = filter_source(seg.source(local), source_spec)
                if src is not None:
                    hit["_source"] = src
                hits.append(hit)
            if hits:
                max_score = hits[0]["_score"]
            hits = hits[from_: from_ + size]
        # size=0: count-only request — null max_score, like the host path

        return {
            "took": int((_time.monotonic() - t0) * 1000),
            "timed_out": False,
            "_shards": {"total": S, "successful": S, "skipped": 0,
                        "failed": 0},
            "hits": {"total": {"value": total, "relation": "eq"},
                     "max_score": max_score,
                     "hits": hits},
        }


def sharded_metric_reduce(mesh: Mesh, axis: str = "shards"):
    """[S, 5] per-shard metric partials (sum, count, min, max, total) ->
    one replicated [5] via ICI collectives — the device-side
    InternalAggregations.reduce for the metric family
    (SearchPhaseController.reducedQueryPhase riding the mesh instead of
    the coordinator's heap)."""

    @partial(shard_map, mesh=mesh, in_specs=P(axis, None), out_specs=P())
    def reduce(parts):
        row = parts[0]
        return jnp.stack([
            lax.psum(row[0], axis),
            lax.psum(row[1], axis),
            lax.pmin(row[2], axis),
            lax.pmax(row[3], axis),
            lax.psum(row[4], axis),
        ])

    return reduce


_MESH_METRICS = {"sum", "avg", "min", "max", "value_count", "stats"}


def sharded_impact_topk(mesh: Mesh, *, n_pad: int, budget: int, k: int,
                        axis: str = "shards"):
    """Build the jitted one-step distributed query: every device scores
    its own shard's postings block FROM ITS PRECOMPUTED IMPACT COLUMN
    (no norm recomputation — the port of ROADMAP item 1's mesh
    leftover) and the global top-k is reduced with an all-gather over
    the mesh axis.

    Inputs (per call): the ``prepare_match_query`` shard-stacked arrays
    [S, ...] for offsets/doc_ids/impacts/term_ids/active/idfs/weights.
    Returns (scores[k], global_doc_ids[k]) replicated on all devices;
    global doc id = shard * n_pad + local id, so ties break by
    (score desc, shard asc, local doc asc) — the coordinator merge
    order.  Scores are byte-identical to the host path's (same impact
    table, same accumulation order), pinned in tests/test_dist_search.py.
    """

    def local_step(offsets, doc_ids, impacts, tids, active, idfs,
                   weights):
        # shard_map hands each device a [1, ...] block — drop the axis
        scores = bm25_ops.impact_scores(  # engine-ok: mesh backend lowering of the unified engine
            offsets[0], doc_ids[0], impacts[0], tids[0], active[0],
            idfs[0], weights[0], n_pad=n_pad, budget=budget)
        vals, idx = lax.top_k(scores, k)
        shard = lax.axis_index(axis)
        gids = shard.astype(jnp.int64) * n_pad + idx
        all_vals = lax.all_gather(vals, axis)     # [S, k] on every device
        all_gids = lax.all_gather(gids, axis)
        fv, fi = lax.top_k(all_vals.reshape(-1), k)
        return fv, all_gids.reshape(-1)[fi]

    spec = P(axis)
    # check_vma=False: the outputs ARE replicated (all_gather + identical
    # re-top-k on every device) but the varying-mesh-axes checker cannot
    # infer that statically.
    fn = shard_map(local_step, mesh=mesh,
                   in_specs=(spec,) * 7,
                   out_specs=(P(), P()),
                   check_vma=False)
    return jax.jit(fn)
