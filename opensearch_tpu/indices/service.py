"""Per-node index registry: index lifecycle, shard routing, document and
search entry points.

Analog of ``indices/IndicesService.java`` + ``index/IndexService.java`` +
``cluster/routing/OperationRouting.java``: an index is N shard engines;
writes route by murmur3(_id or routing) mod num_shards; node-local search
runs over ALL shards' segments in one ShardSearcher — which makes scoring
stats global (stronger than the reference's per-shard idf under plain
query_then_fetch) and reuses the segment merge path as the shard merge.
The mesh/distributed path (parallel/dist_search.py) is the multi-host
story; this service is the per-node control plane under it.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import threading
import time
import uuid
from typing import Optional

from opensearch_tpu.common.errors import (
    IllegalArgumentError,
    IndexAlreadyExistsError,
    IndexNotFoundError,
    OpenSearchTpuError,
    ResourceAlreadyExistsError,
    ResourceNotFoundError,
    ValidationError,
)
from opensearch_tpu.index.engine import InternalEngine, OpResult
from opensearch_tpu.mapping.mapper import DocumentMapper
from opensearch_tpu.search.executor import ShardSearcher


def murmur3_32(data: bytes, seed: int = 0) -> int:
    """murmur3 x86 32-bit (the reference's Murmur3HashFunction routing
    hash family; value compatibility with the JVM impl is not required —
    stability within this engine is)."""
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = seed
    length = len(data)
    rounded = length & ~3
    for i in range(0, rounded, 4):
        k = int.from_bytes(data[i: i + 4], "little")
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
        h = ((h << 13) | (h >> 19)) & 0xFFFFFFFF
        h = (h * 5 + 0xE6546B64) & 0xFFFFFFFF
    k = 0
    tail = data[rounded:]
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
    h ^= length
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return h


_INDEX_NAME_FORBIDDEN = set('\\/*?"<>| ,#:')


def deep_merge_doc(base: dict, patch: dict) -> dict:
    """Recursive partial-document merge for _update: nested objects merge
    key-by-key, everything else (incl. arrays) replaces
    (XContentHelper.update / UpdateHelper semantics)."""
    out = dict(base)
    for k, v in patch.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = deep_merge_doc(out[k], v)
        else:
            out[k] = v
    return out


# cluster-level slowlog threshold defaults, keyed by the full dotted
# setting (e.g. "search.slowlog.threshold.query.warn") — populated by
# the node's _cluster/settings consumers; per-index settings override
# (the reference's index-setting-with-node-default layering)
SLOWLOG_DEFAULTS: dict = {}

# severity order matters: the slowest matching threshold wins, highest
# level first (SearchSlowLog's warn > info > debug > trace)
_SLOWLOG_LEVELS = (("warn", 30), ("info", 20), ("debug", 10),
                   ("trace", 5))


def _parse_millis(v) -> int:
    """Time expression -> ms ("500ms", "1.5s", "1m", "1d", bare
    number=ms); -1 disables (the slow-log convention).  Unparseable
    values log a warning once and disable rather than failing queries."""
    if isinstance(v, (int, float)):
        return int(v)
    s = str(v).strip().lower()
    for suffix, mult in (("ms", 1), ("s", 1000), ("m", 60_000),
                         ("h", 3_600_000), ("d", 86_400_000)):
        if s.endswith(suffix):
            try:
                return int(float(s[: -len(suffix)]) * mult)
            except ValueError:
                break
    try:
        return int(float(s))
    except ValueError:
        import logging
        logging.getLogger("opensearch_tpu.settings").warning(
            "unparseable time value [%s]; threshold disabled", v)
        return -1


def shard_id_for(doc_id: str, routing: Optional[str], num_shards: int) -> int:
    """THE routing decision — every layer (coordinator + data node) must
    agree on it, so it lives in exactly one place."""
    key = (routing if routing is not None else str(doc_id)).encode()
    return murmur3_32(key) % num_shards


class IndexService:
    """One index: mapper + N shard engines + searcher cache."""

    def __init__(self, name: str, data_path: str, settings: dict,
                 mappings: Optional[dict], persist_meta=None,
                 local_shard_ids: Optional[list[int]] = None):
        self.name = name
        self.data_path = data_path
        self.settings = settings
        self._persist_meta = persist_meta
        self.num_shards = int(settings.get("number_of_shards", 1))
        self.num_replicas = int(settings.get("number_of_replicas", 0))
        if self.num_shards < 1:
            raise IllegalArgumentError(
                f"number_of_shards must be >= 1, got {self.num_shards}")
        self.creation_date = int(time.time() * 1000)  # wall-clock: timestamp
        self.uuid = uuid.uuid4().hex[:22]
        self.mapper = DocumentMapper(mappings or {})
        self._durability = settings.get("translog", {}).get("durability",
                                                            "request")
        # index.codec (ref index/codec/CodecService.java:46): default vs
        # best_compression, fixed at index creation like the reference
        self._codec = str(settings.get("codec", "default"))
        from opensearch_tpu.index.store import CODECS
        if self._codec not in CODECS:
            raise IllegalArgumentError(
                f"unknown value for [index.codec]: [{self._codec}] — "
                f"supported: {list(CODECS)}")
        # in cluster mode a node hosts only the shards routed to it
        # (IndicesClusterStateService analog); standalone hosts all
        if local_shard_ids is None:
            local_shard_ids = list(range(self.num_shards))
        self.local_shards: dict[int, InternalEngine] = {
            s: self._open_shard(s) for s in sorted(local_shard_ids)}
        self._lock = threading.RLock()
        self._searcher: Optional[ShardSearcher] = None
        self._mesh_searcher = None
        # search-visibility generation: bumped whenever the searchable
        # segment set may have changed (refresh / checkpoint install /
        # shard set change / mapping change).  The request cache keys on
        # it, so stale entries stop matching the moment anything moves
        # (IndicesRequestCache's reader-generation key).
        self._reader_gen = 0

    def _open_shard(self, shard_id: int) -> InternalEngine:
        return InternalEngine(os.path.join(self.data_path, str(shard_id)),
                              self.mapper, index_name=self.name,
                              shard_id=shard_id,
                              durability=self._durability,
                              codec=self._codec)

    @property
    def shards(self) -> list[InternalEngine]:
        return list(self.local_shards.values())

    def add_local_shard(self, shard_id: int):
        with self._lock:
            if shard_id not in self.local_shards:
                self.local_shards[shard_id] = self._open_shard(shard_id)
                self._searcher = None
                self._mesh_searcher = None

    def remove_local_shard(self, shard_id: int):
        with self._lock:
            engine = self.local_shards.pop(shard_id, None)
            if engine is not None:
                engine.close()
                self._searcher = None
                self._mesh_searcher = None

    def reset_local_shard(self, shard_id: int):
        """Drop a shard copy's on-disk state entirely and reopen empty —
        the corruption-failover primitive: a copy that failed store
        verification is discarded (corruption markers included) and
        re-recovered from the primary (the reference deletes the shard
        directory before re-allocating a failed copy there)."""
        import shutil
        with self._lock:
            engine = self.local_shards.pop(shard_id, None)
            if engine is not None:
                engine.close()
            shutil.rmtree(os.path.join(self.data_path, str(shard_id)),
                          ignore_errors=True)
            self.local_shards[shard_id] = self._open_shard(shard_id)
            self._searcher = None
            self._mesh_searcher = None
            self._reader_gen += 1

    def corrupted_shards(self) -> dict:
        """shard_id -> corruption markers/verdicts for local copies that
        failed store verification (the red-status evidence
        ``_cluster/health`` and ``_cat/indices`` surface)."""
        from opensearch_tpu.index.store import find_corruption_markers
        out = {}
        for sid, engine in sorted(self.local_shards.items()):
            markers = find_corruption_markers(
                os.path.join(engine.data_path, "segments"))
            if engine.corruption is not None and not markers:
                markers = [{"reason": str(engine.corruption)}]
            if markers:
                out[sid] = markers
        return out

    # -- routing ----------------------------------------------------------

    def route_shard(self, doc_id: str, routing: Optional[str] = None) -> int:
        return shard_id_for(doc_id, routing, self.num_shards)

    def engine_for(self, shard_id: int) -> InternalEngine:
        engine = self.local_shards.get(shard_id)
        if engine is None:
            from opensearch_tpu.common.errors import ShardNotFoundError
            raise ShardNotFoundError(
                f"shard [{self.name}][{shard_id}] is not on this node")
        return engine

    def route(self, doc_id: str, routing: Optional[str] = None) -> InternalEngine:
        return self.engine_for(self.route_shard(doc_id, routing))

    # -- document ops -----------------------------------------------------

    def _check_write_block(self):
        if self.settings.get("remote_snapshot"):
            from opensearch_tpu.common.errors import ClusterBlockException
            raise ClusterBlockException(
                f"index [{self.name}] blocked by: [FORBIDDEN/13/remote "
                "index is read-only (searchable snapshot)]")
        blocked = self.settings.get(
            "index.blocks.write",
            (self.settings.get("blocks") or {}).get("write", False))
        if str(blocked).lower() == "true":
            from opensearch_tpu.common.errors import ClusterBlockException
            raise ClusterBlockException(
                f"index [{self.name}] blocked by: [FORBIDDEN/8/index "
                "write (api)]")

    # node-level tracker injected by IndicesService at registration;
    # None = standalone IndexService (tests) with no admission control
    indexing_pressure = None

    def index_doc(self, doc_id: Optional[str], source: dict,
                  routing: Optional[str] = None,
                  op_bytes: Optional[int] = None, **kw) -> OpResult:
        """``op_bytes``: the caller's known wire size (REST passes the
        raw body length so the hot path never re-serializes just to
        measure)."""
        self._check_write_block()
        t0 = time.monotonic()
        if doc_id is None:
            doc_id = uuid.uuid4().hex[:20]
        shard = self.route_shard(str(doc_id), routing)
        engine = self.engine_for(shard)
        if self.indexing_pressure is not None:
            if op_bytes is None:
                op_bytes = len(json.dumps(source, separators=(",", ":")))
            with self.indexing_pressure.coordinating((self.name, shard),
                                                     int(op_bytes)):
                result = engine.index(str(doc_id), source,
                                      routing=routing, **kw)
                engine.ensure_synced()
        else:
            result = engine.index(str(doc_id), source, routing=routing,
                                  **kw)
            engine.ensure_synced()
        self._maybe_indexing_slowlog(
            int((time.monotonic() - t0) * 1000), result.doc_id, source)
        return result

    def delete_doc(self, doc_id: str, routing: Optional[str] = None,
                   **kw) -> OpResult:
        self._check_write_block()
        engine = self.route(doc_id, routing)
        result = engine.delete(str(doc_id), **kw)
        engine.ensure_synced()
        return result

    def get_doc(self, doc_id: str, routing: Optional[str] = None,
                realtime: bool = True) -> Optional[dict]:
        return self.route(doc_id, routing).get(str(doc_id), realtime=realtime)

    def bulk(self, ops: list[tuple]) -> list[dict]:
        """ops: [(action, doc_id, source, params)] — per-item results, errors
        reported per item like TransportShardBulkAction (never aborts the
        batch)."""
        from opensearch_tpu.common.errors import OpenSearchTpuError

        results = []
        touched = set()
        for action, doc_id, source, params in ops:
            try:
                if doc_id == "":
                    raise IllegalArgumentError(
                        "if _id is specified it must not be empty")
                if action in ("index", "create"):
                    if action == "create" and doc_id is not None:
                        existing = self.get_doc(doc_id,
                                                params.get("routing"))
                        if existing is not None:
                            raise ValidationError(
                                f"[{doc_id}]: version conflict, document "
                                "already exists")
                    cas = {k: int(params[k])
                           for k in ("if_seq_no", "if_primary_term")
                           if params.get(k) is not None}
                    r = self.index_doc(doc_id, source,
                                       routing=params.get("routing"),
                                       op_bytes=params.get("op_bytes"),
                                       **cas)
                    results.append({action: {
                        "_index": self.name, "_id": r.doc_id,
                        "_version": r.version, "_seq_no": r.seq_no,
                        "_primary_term": r.primary_term,
                        "result": r.result,
                        "status": 201 if r.result == "created" else 200}})
                elif action == "delete":
                    r = self.delete_doc(doc_id, routing=params.get("routing"))
                    results.append({"delete": {
                        "_index": self.name, "_id": r.doc_id,
                        "_version": r.version, "_seq_no": r.seq_no,
                        "_primary_term": r.primary_term,
                        "result": r.result,
                        "status": 404 if r.result == "not_found" else 200}})
                elif action == "update":
                    cur = self.get_doc(doc_id, params.get("routing"))
                    from opensearch_tpu.common.errors import (
                        VersionConflictError)
                    if params.get("if_seq_no") is not None:
                        cur_seq = cur["_seq_no"] if cur is not None else -1
                        if int(params["if_seq_no"]) != cur_seq:
                            raise VersionConflictError(
                                doc_id, f"seq_no [{params['if_seq_no']}]",
                                f"seq_no [{cur_seq}]")
                    if params.get("if_primary_term") is not None:
                        cur_term = (cur.get("_primary_term", 1)
                                    if cur is not None else 0)
                        if int(params["if_primary_term"]) != cur_term:
                            raise VersionConflictError(
                                doc_id,
                                f"primary_term "
                                f"[{params['if_primary_term']}]",
                                f"primary_term [{cur_term}]")
                    if cur is not None and "_source" not in cur:
                        raise IllegalArgumentError(
                            f"[{doc_id}]: source is missing — partial "
                            "updates require [_source] to be enabled")
                    if cur is None:
                        if "upsert" in source:
                            merged = source["upsert"]
                        else:
                            from opensearch_tpu.common.errors import (
                                DocumentMissingError)
                            raise DocumentMissingError(self.name, doc_id)
                    else:
                        merged = deep_merge_doc(cur["_source"],
                                                source.get("doc", {}))
                    r = self.index_doc(doc_id, merged,
                                       routing=params.get("routing"))
                    src_spec = params.get("_source")
                    if src_spec is None and isinstance(source, dict):
                        src_spec = source.get("_source")
                    if src_spec:
                        from opensearch_tpu.search.fetch import (
                            filter_source)
                        spec = src_spec
                        if spec in ("true", "false"):
                            spec = spec == "true"
                        elif not isinstance(spec, bool):
                            spec = (spec.split(",")
                                    if isinstance(spec, str) else spec)
                        results.append({"update": {
                            "_index": self.name, "_id": r.doc_id,
                            "_version": r.version, "_seq_no": r.seq_no,
                            "result": "updated", "status": 200,
                            "get": {"found": True,
                                    "_source": filter_source(merged,
                                                             spec)}}})
                        continue
                    results.append({"update": {
                        "_index": self.name, "_id": r.doc_id,
                        "_version": r.version, "result": "updated",
                        "status": 200}})
                else:
                    raise ValidationError(f"unknown bulk action [{action}]")
                touched.add(action)
            except OpenSearchTpuError as e:
                results.append({action: {
                    "_index": self.name, "_id": doc_id, "status": e.status,
                    "error": e.to_xcontent()["error"]}})
        return results

    # -- search -----------------------------------------------------------

    def _dirty(self):
        from opensearch_tpu.indices.request_cache import request_cache
        with self._lock:
            self._searcher = None
            self._reader_gen += 1
        # eager cleanup: the generation bump already unreachable-izes the
        # old entries; dropping them keeps memory tracking visibility
        request_cache().invalidate_service(self.uuid)

    def refresh(self):
        for engine in self.shards:
            engine.refresh()
        self._dirty()

    def refresh_doc_shard(self, doc_id: str, routing: Optional[str] = None):
        """?refresh=true on a single-document write refreshes ONLY the
        owning shard (RestActions write-refresh semantics: other shards'
        pending ops stay invisible)."""
        self.route(doc_id, routing).refresh()
        self._dirty()

    def invalidate_searcher(self):
        """Drop the cached node-local searcher (segments changed outside
        the write path, e.g. a replica installed a checkpoint)."""
        self._dirty()

    def save_meta(self):
        """Persist the CURRENT mapping (incl. dynamically-added fields) —
        after a flush the translog can no longer re-derive them on replay."""
        if self._persist_meta is not None:
            self._persist_meta(self.name, self.settings,
                               self.mapper.to_mapping())

    # set by the node when a blob-repository registry exists; consulted
    # at flush time for remote-store mirroring (RemoteStoreRefreshListener
    # analog, at flush granularity).  repo_mutex_fn serializes against
    # the snapshot service's blob GC.
    repo_resolver = None
    repo_mutex_fn = None

    def _remote_repo(self):
        rs = self.settings.get("remote_store") or {}
        enabled = rs.get("enabled") in (True, "true")
        repo_name = rs.get("repository")
        if not enabled or not repo_name or self.repo_resolver is None:
            return None
        try:
            return self.repo_resolver(repo_name)
        except OpenSearchTpuError:
            # a vanished repository must NEVER block local durability —
            # flush proceeds, mirroring resumes when the repo returns
            import logging
            logging.getLogger("opensearch_tpu.remote_store").warning(
                "[%s] remote store repository [%s] unavailable; "
                "flushing locally only", self.name, repo_name)
            return None

    def flush(self):
        # local flush under the index lock (a concurrent flush's
        # merge-GC could delete segment files mid-upload); REMOTE
        # uploads happen after release so slow blob stores never stall
        # searches/shard ops, under the repo mutex so the snapshot GC
        # can't collect just-written blobs.  A per-index flush
        # generation orders uploads: a flush that lost the mutex race to
        # a NEWER flush skips its (stale) manifests entirely instead of
        # rolling the mirror back.
        if self.settings.get("remote_snapshot"):
            return                   # data lives in the repository
        with self._lock:
            self.save_meta()
            self._flush_gen = getattr(self, "_flush_gen", 0) + 1
            my_gen = self._flush_gen
            commits = {sid: engine.flush()
                       for sid, engine in sorted(
                           self.local_shards.items())}
        repo = self._remote_repo()
        if repo is None:
            return
        import logging

        from opensearch_tpu.index.remote_store import upload_shard
        mutex = (self.repo_mutex_fn(repo.name)
                 if self.repo_mutex_fn else None)
        try:
            if mutex is not None:
                mutex.acquire()
            # PER-SHARD generation marks: a shard whose manifest a
            # newer flush already wrote is never overwritten by an
            # older one, even when that newer flush partially failed
            shard_gens = getattr(self, "_uploaded_shard_gens", None)
            if shard_gens is None:
                shard_gens = self._uploaded_shard_gens = {}
            all_ok = True
            for shard_id, commit in commits.items():
                engine = self.local_shards.get(shard_id)
                if engine is None:
                    continue
                if shard_gens.get(shard_id, 0) > my_gen:
                    continue         # newer manifest already mirrored
                try:
                    upload_shard(repo, self.name, shard_id, engine,
                                 commit)
                    shard_gens[shard_id] = my_gen
                except Exception as e:  # noqa: BLE001 — best effort
                    # mirroring is BEST-EFFORT: local durability already
                    # succeeded; the mirror stays at its previous commit
                    all_ok = False
                    logging.getLogger(
                        "opensearch_tpu.remote_store").warning(
                        "[%s][%s] remote upload failed: %s", self.name,
                        shard_id, e)
            if (all_ok and my_gen == self._flush_gen
                    and getattr(self, "_meta_gen", 0) < my_gen):
                # meta only advances WITH the data, and only from the
                # LATEST flush — a stale flush writing current live
                # mappings beside mixed-generation manifests would
                # restore segments under the wrong schema
                import json as _json
                try:
                    repo.store.container(
                        f"remote/{self.name}").write_blob(
                        "_meta.json", _json.dumps({
                            "settings": dict(self.settings),
                            "mappings": self.mapper.to_mapping()
                        }).encode())
                    self._meta_gen = my_gen
                except Exception as e:  # noqa: BLE001 — best effort
                    logging.getLogger(
                        "opensearch_tpu.remote_store").warning(
                        "[%s] remote meta upload failed: %s",
                        self.name, e)
        finally:
            if mutex is not None:
                mutex.release()

    def force_merge(self, max_num_segments: int = 1):
        self._check_write_block()   # would write merged files locally
        for engine in self.shards:
            engine.force_merge(max_num_segments)
        self._dirty()

    def searcher(self) -> ShardSearcher:
        """Node-local search view: every shard's segments under one
        searcher (global stats; segment merge == shard merge).  Cached
        between refreshes — NRT visibility changes only at refresh."""
        with self._lock:
            if self._searcher is None:
                segs = []
                for engine in self.shards:
                    segs.extend(engine.acquire_searcher().segments)
                self._searcher = ShardSearcher(segs, self.mapper,
                                               index_name=self.name)
            return self._searcher

    def update_settings(self, flat: dict):
        """Apply a dynamic settings update; static settings reject
        (IndexScopedSettings.NOT_DYNAMIC check)."""
        for key, value in flat.items():
            bare = key[6:] if key.startswith("index.") else key
            if bare in ("number_of_shards", "routing_partition_size"):
                raise IllegalArgumentError(
                    f"final [{key}] setting: this setting is not "
                    "updateable")
            if bare == "number_of_replicas":
                self.num_replicas = int(value)
            self.settings[f"index.{bare}"] = value
        if self._persist_meta is not None:
            self._persist_meta(self.name, self.settings,
                               self.get_mapping().get("mappings"))

    def index_setting(self, key: str, default):
        """Per-index setting lookup accepting the dotted, bare, and
        nested-object key forms the create body may use."""
        v = self.settings.get(f"index.{key}", self.settings.get(key))
        if v is None:
            for root in (self.settings.get("index"), self.settings):
                node = root
                for part in key.split("."):
                    node = (node.get(part)
                            if isinstance(node, dict) else None)
                    if node is None:
                        break
                if node is not None:
                    v = node
                    break
        return default if v is None else v

    def _check_search_limits(self, body: dict):
        """Per-index request-size guards (IndexSettings.MAX_* family)."""
        mrw = int(self.index_setting("max_result_window", 10000))
        window = int(body.get("from", 0) or 0) + int(
            body.get("size", 10) if body.get("size") is not None else 10)
        if window > mrw:
            raise IllegalArgumentError(
                f"Result window is too large, from + size must be less "
                f"than or equal to: [{mrw}] but was [{window}]. See the "
                "scroll api for a more efficient way to request large "
                "data sets.")
        dvf = body.get("docvalue_fields") or []
        max_dvf = int(self.index_setting("max_docvalue_fields_search", 100))
        if len(dvf) > max_dvf:
            raise IllegalArgumentError(
                f"Trying to retrieve too many docvalue_fields. Must be "
                f"less than or equal to: [{max_dvf}] but was "
                f"[{len(dvf)}]. This limit can be set by changing the "
                "[index.max_docvalue_fields_search] index level setting.")
        sf = body.get("script_fields") or {}
        max_sf = int(self.index_setting("max_script_fields", 32))
        if len(sf) > max_sf:
            raise IllegalArgumentError(
                f"Trying to retrieve too many script_fields. Must be "
                f"less than or equal to: [{max_sf}] but was [{len(sf)}]. "
                "This limit can be set by changing the "
                "[index.max_script_fields] index level setting.")
        max_tc = int(self.index_setting("max_terms_count", 65536))

        def check_terms(node):
            if isinstance(node, dict):
                tq = node.get("terms")
                if isinstance(tq, dict):
                    for f, vals in tq.items():
                        if isinstance(vals, list) and len(vals) > max_tc:
                            raise IllegalArgumentError(
                                f"The number of terms [{len(vals)}] "
                                "used in the Terms Query request has "
                                "exceeded the allowed maximum of "
                                f"[{max_tc}]. This maximum can be set "
                                "by changing the [index.max_terms_count] "
                                "index level setting.")
                for v in node.values():
                    check_terms(v)
            elif isinstance(node, list):
                for v in node:
                    check_terms(v)
        if body.get("query") is not None:
            check_terms(body["query"])
        rescore = body.get("rescore")
        if rescore:
            spec = rescore[0] if isinstance(rescore, list) else rescore
            window = int(spec.get("window_size", 10))
            max_rw = int(self.index_setting("max_rescore_window", 10000))
            if window > max_rw:
                raise IllegalArgumentError(
                    f"Rescore window [{window}] is too large. It must "
                    f"be less than [{max_rw}]. This prevents allocating "
                    "massive heaps for storing the results to be "
                    "rescored. This limit can be set by changing the "
                    "[index.max_rescore_window] index level setting.")

    def search(self, body: Optional[dict] = None, *,
               agg_partials: bool = False) -> dict:
        body = dict(body or {})
        # request-level cache directive (the ?request_cache= param; the
        # REST layer validated it) must not leak into execution or the
        # cache key
        explicit_cache = body.pop("request_cache", None)
        self._check_search_limits(body)
        from opensearch_tpu.search import insights
        if self.should_cache_request(body, explicit_cache, agg_partials):
            from opensearch_tpu.indices.request_cache import request_cache
            resp, hit = request_cache().get_or_compute(
                index=self.name, svc_uuid=self.uuid, shard_key="_local",
                reader_gen=self._reader_gen, body=body,
                compute=lambda: self._execute_search(body, agg_partials))
            if hit:
                # the executor never ran: synthesize the insight record
                # here (the cache hit IS the workload evidence)
                insights.emit(
                    signature=insights.canonical_query(
                        body.get("query")),
                    scored=insights.scored_for_body(body),
                    took_ms=float(resp.get("took", 0)),
                    execution_path="cached", plan_cache="hit",
                    request_cache="hit", index=self.name)
            else:
                insights.annotate_last(request_cache="miss",
                                       index=self.name)
        else:
            resp = self._execute_search(body, agg_partials)
            insights.annotate_last(request_cache="bypass",
                                   index=self.name)
        self._maybe_slowlog(body, resp)
        return resp

    def _execute_search(self, body: dict, agg_partials: bool) -> dict:
        # ONE engine entry for every backend: the mesh router, the
        # continuous batcher, the host fast path and the device kernels
        # are decisions inside QueryEngine.execute, not separately-wired
        # code paths here (search/engine.py; tools/check_execution_paths
        # keeps new paths from bypassing it)
        from opensearch_tpu.common.device_health import \
            DeviceDegradedError
        from opensearch_tpu.search.engine import query_engine
        try:
            resp = query_engine().execute(self.searcher(), body,
                                          agg_partials=agg_partials,
                                          service=self)
        except DeviceDegradedError as exc:
            # an accelerator fault with no byte-identical host fallback
            # degrades to PR-2-style partial results (the same shape a
            # dead shard copy produces) instead of a 500 — unless the
            # client asked for all-or-nothing semantics
            if body.get("allow_partial_search_results") is False:
                raise
            return self._device_degraded_response(body, exc)
        resp["_shards"] = {"total": self.num_shards,
                           "successful": self.num_shards,
                           "skipped": 0, "failed": 0}
        return resp

    def _device_degraded_response(self, body: dict,
                                  exc: BaseException) -> dict:
        """Partial-results response for a device-degraded search: every
        local shard reports the device failure in ``_shards.failures[]``
        (ShardSearchFailure shape), hits are empty, and the insight
        record carries outcome ``device_degraded`` so the workload
        attribution shows WHO was degraded."""
        from opensearch_tpu.common.telemetry import metrics
        from opensearch_tpu.search import insights
        from opensearch_tpu.search.executor import (shard_failure_entry,
                                                    shards_section)
        metrics().counter("device.degraded_searches").inc()
        with self._lock:
            shard_ids = sorted(self.local_shards) or [0]
        failures = [shard_failure_entry(self.name, s, None, exc)
                    for s in shard_ids]
        insights.emit(
            signature=insights.canonical_query(body.get("query")),
            scored=insights.scored_for_body(body),
            took_ms=0.0, execution_path="device",
            plan_cache="miss", outcome="device_degraded")
        return {
            "took": 0,
            "timed_out": False,
            "_shards": shards_section(len(shard_ids), failures=failures),
            "hits": {"total": {"value": 0, "relation": "gte"},
                     "max_score": None, "hits": []},
        }

    def should_cache_request(self, body: dict, explicit,
                             agg_partials: bool = False) -> bool:
        """IndicesRequestCache admission policy (the reference's
        canCache): profile/PIT never cache; an explicit request-level
        ``request_cache`` wins over the ``index.requests.cache.enable``
        index setting; by default only hit-less (size=0) requests cache,
        like the reference."""
        if agg_partials:
            return False         # device partials aren't serializable
        if body.get("profile") or body.get("pit"):
            return False
        if explicit is not None:
            return bool(explicit)
        enabled = str(self.index_setting(
            "requests.cache.enable", True)).lower() != "false"
        size = int(body.get("size", 10)
                   if body.get("size") is not None else 10)
        return enabled and size == 0

    def _slowlog_threshold(self, key: str):
        """Per-index setting (either [index.]-prefixed or bare) over the
        cluster-level default (SLOWLOG_DEFAULTS)."""
        return self.index_setting(key, SLOWLOG_DEFAULTS.get(key))

    def _maybe_slowlog(self, body: dict, resp: dict):
        """index.search.slowlog.threshold.query.{warn,info,debug,trace}
        (ref index/SearchSlowLog.java:61): queries slower than the
        threshold log with the source at the matching level; the most
        severe matching threshold wins.  Dynamic: per-index via
        PUT /{index}/_settings, cluster default via _cluster/settings."""
        import logging
        took = resp.get("took", 0)
        for level, py_level in _SLOWLOG_LEVELS:
            raw = self._slowlog_threshold(
                f"search.slowlog.threshold.query.{level}")
            if raw is None:
                continue
            thr = _parse_millis(raw)
            if thr >= 0 and took >= thr:
                logging.getLogger(
                    "opensearch_tpu.index.search.slowlog").log(
                    py_level, "[%s] took[%dms], timed_out[%s], "
                    "source[%s]", self.name, took,
                    str(bool(resp.get("timed_out"))).lower(),
                    json.dumps(body.get("query") or {})[:256])
                # a tripped slow log is a flight-recorder trigger: the
                # capture carries the query source and — when the slow
                # query ran with profile:true — its phase breakdown,
                # so the slow query is diagnosable after the fact
                from opensearch_tpu.common.telemetry import \
                    flight_recorder
                detail = {"index": self.name, "took_ms": int(took),
                          "level": level,
                          "source": json.dumps(
                              body.get("query") or {})[:256]}
                if resp.get("profile"):
                    detail["profile"] = resp["profile"]
                flight_recorder().record(
                    "slow_log",
                    f"[{self.name}] search took {took}ms >= "
                    f"{level} threshold [{raw}]", detail)
                break

    def _maybe_indexing_slowlog(self, took_ms: int, doc_id: str,
                                source: dict):
        """index.indexing.slowlog.threshold.index.{warn,info,debug,trace}
        (ref index/IndexingSlowLog.java:64): writes slower than the
        threshold log doc id + truncated source."""
        import logging
        for level, py_level in _SLOWLOG_LEVELS:
            raw = self._slowlog_threshold(
                f"indexing.slowlog.threshold.index.{level}")
            if raw is None:
                continue
            thr = _parse_millis(raw)
            if thr >= 0 and took_ms >= thr:
                max_chars = int(self.index_setting(
                    "indexing.slowlog.source", 1000))
                logging.getLogger(
                    "opensearch_tpu.index.indexing.slowlog").log(
                    py_level, "[%s/%s] took[%dms], source[%s]",
                    self.name, doc_id, took_ms,
                    json.dumps(source)[:max_chars])
                break

    # -- device-mesh search path (index.search.mesh: true) ----------------

    def _use_mesh(self, body: dict) -> bool:
        """Route through the device-collective scatter-gather when the
        index opted in, shards fit the mesh, and the request is a scored
        top-k (sort/aggs reduce on the host path for now).  Semantics
        match the multi-node cluster path: per-shard scoring stats
        (query_then_fetch), vs the merged-searcher host path's global
        stats."""
        flag = self.settings.get("search.mesh")
        if flag in (None, False, "false"):
            return False
        if len(self.local_shards) < 2:
            return False
        if body.get("sort") is not None:
            return False
        if body.get("profile"):
            # phase attribution instruments the host pipeline; profiled
            # requests route there (hits are parity-tested identical)
            return False
        q = body.get("query")
        if isinstance(q, dict) and "hybrid" in q:
            return False       # hybrid dispatches inside ShardSearcher
        import jax

        return len(jax.devices()) >= len(self.local_shards)

    def _mesh_degrade(self, body: dict, reason: str) -> dict:
        """Demote a mesh request to the counted host scatter fallback:
        an unavailable shard_map, a mesh that cannot be built (member
        loss / too few devices), an open ``mesh`` circuit breaker, or a
        device error mid-collective all land here — the request
        degrades (same per-shard scoring stats, coordinator-order
        merge), never 500s."""
        from opensearch_tpu.common.telemetry import metrics
        from opensearch_tpu.search import insights
        metrics().counter("search.mesh.fallback").inc()
        with insights.suppressed():
            resp = self._host_scatter_search(body)
        insights.emit(
            signature=insights.canonical_query(body.get("query")),
            scored=True, took_ms=float(resp.get("took", 0)),
            execution_path="mesh_fallback", plan_cache="miss")
        return resp

    def _mesh_search(self, body: dict) -> dict:
        from opensearch_tpu.common.device_health import (device_health,
                                                         is_device_error)
        from opensearch_tpu.search import insights
        health = device_health()
        try:
            from opensearch_tpu.parallel import dist_search
            if not dist_search.MESH_AVAILABLE:
                raise ImportError("no shard_map in this jax")
            MeshSearcher = dist_search.MeshSearcher
        except ImportError:
            # graceful degradation: a jax without any shard_map spelling
            # (see parallel/dist_search.py) must not 500 the request —
            # the host scatter preserves mesh semantics minus the ICI
            # collective, and the fallback is a counted, alertable event
            return self._mesh_degrade(body, "shard_map unavailable")
        if not health.allow("mesh"):
            # open mesh breaker: don't re-attempt a failing collective
            # per request — demote until a half-open probe re-closes it
            return self._mesh_degrade(body, "mesh circuit breaker open")

        try:
            with self._lock:
                shards = [self.local_shards[s].acquire_searcher()
                          for s in sorted(self.local_shards)]
                if (self._mesh_searcher is None
                        or len(self._mesh_searcher.shards)
                        != len(shards)):
                    self._mesh_searcher = MeshSearcher(shards)
                else:
                    # keep the per-device staging + compiled merge
                    # caches across refreshes; only the searcher
                    # snapshots change
                    self._mesh_searcher.update_shards(shards)
                ms = self._mesh_searcher
        except Exception as exc:
            # a mesh that cannot be BUILT (fewer live devices than
            # shards = member loss) is a mesh fault, not a query fault
            with self._lock:
                self._mesh_searcher = None
            health.record_failure("mesh", exc)   # counted: device.errors
            return self._mesh_degrade(
                body, f"mesh construction failed: {exc}")

        def collective(fn):
            """Run one mesh collective; device errors demote to the
            host scatter fallback (counted) instead of raising."""
            try:
                out = fn()
            except Exception as exc:
                if not is_device_error(exc):
                    raise
                health.record_failure("mesh", exc)  # counted: device.errors
                return None
            health.record_success("mesh")
            return out

        aggs_json = body.get("aggs") or body.get("aggregations")
        if not aggs_json and not body.get("suggest"):
            resp = collective(lambda: ms.search(body))
            if resp is None:
                return self._mesh_degrade(body, "mesh collective failed")
            insights.emit(
                signature=insights.canonical_query(body.get("query")),
                scored=True, took_ms=float(resp.get("took", 0)),
                execution_path="mesh", plan_cache="miss")
            return resp
        if (aggs_json and not body.get("suggest")
                and int(body.get("size", 10)) == 0
                and body.get("min_score") is None
                and ms.supports_mesh_aggs(aggs_json)):
            # the metric-agg family reduces ON the mesh (one ICI
            # collective), never serializing per-shard partials
            resp = collective(lambda: ms.mesh_metric_aggs(body,
                                                          aggs_json))
            if resp is None:
                return self._mesh_degrade(body, "mesh collective failed")
            insights.emit(
                signature=insights.canonical_query(body.get("query")),
                scored=False, took_ms=float(resp.get("took", 0)),
                execution_path="mesh", plan_cache="miss")
            return resp
        # device-collective top-k + host-side per-shard partial collect,
        # reduced exactly like the cross-node coordinator (the agg columns
        # are host/default-device resident; the mesh carries the scored
        # merge).  size:0 skips the mesh scored pass entirely — the host
        # collect already produces totals, so running both would execute
        # the query twice for a response whose hits are discarded.
        from opensearch_tpu.search.aggs import reduce_aggs
        from opensearch_tpu.search.suggest import merge_suggest
        collect_body = {"size": 0}
        if aggs_json:
            collect_body["aggs"] = aggs_json
        if body.get("suggest"):
            collect_body["suggest"] = body["suggest"]
        for key in ("query", "min_score"):
            if body.get(key) is not None:
                collect_body[key] = body[key]
        size0 = int(body.get("size", 10)) == 0
        with insights.suppressed():
            # per-shard collect legs of ONE mesh search: the mesh-level
            # record below is the arrival, not its scatter legs
            shard_resps = [s.search(collect_body, agg_partials=True)
                           for s in shards]
        partials = [r.get("aggregation_partials") or {} for r in shard_resps]
        if size0:
            total = sum(r["hits"]["total"]["value"] for r in shard_resps)
            resp = {"took": max((r["took"] for r in shard_resps), default=0),
                    "timed_out": False,
                    "hits": {"total": {"value": total, "relation": "eq"},
                             "max_score": None, "hits": []}}
        else:
            resp = collective(lambda: ms.search(
                {k: v for k, v in body.items()
                 if k not in ("aggs", "aggregations", "suggest")}))
            if resp is None:
                return self._mesh_degrade(body, "mesh collective failed")
        if aggs_json:
            resp["aggregations"] = reduce_aggs(aggs_json, partials)
        if body.get("suggest"):
            resp["suggest"] = merge_suggest(
                [r.get("suggest") for r in shard_resps])
        insights.emit(
            signature=insights.canonical_query(body.get("query")),
            scored=not size0, took_ms=float(resp.get("took", 0)),
            execution_path="mesh", plan_cache="miss")
        return resp

    def _host_scatter_search(self, body: dict) -> dict:
        """Mesh-unavailable fallback: the same scatter-gather the device
        collective performs, on the host — every local shard queries its
        OWN searcher (per-shard scoring stats, query_then_fetch
        semantics identical to the mesh and the multi-node coordinator)
        and the top-k merges with the coordinator's tie-break order."""
        from opensearch_tpu.search.aggs import reduce_aggs
        from opensearch_tpu.search.executor import merge_hit_rows
        from opensearch_tpu.search.suggest import merge_suggest

        t0 = time.monotonic()
        size = int(body.get("size", 10)
                   if body.get("size") is not None else 10)
        from_ = int(body.get("from", 0) or 0)
        aggs_json = body.get("aggs") or body.get("aggregations")
        sub = dict(body)
        sub["from"] = 0
        sub["size"] = from_ + size
        with self._lock:
            searchers = [self.local_shards[s].acquire_searcher()
                         for s in sorted(self.local_shards)]
        shard_resps = [s.search(sub, agg_partials=bool(aggs_json))
                       for s in searchers]
        rows = []
        total = 0
        max_score = None
        for si, r in enumerate(shard_resps):
            for pos, h in enumerate(r["hits"]["hits"]):
                rows.append((h, si, pos))
            total += r["hits"]["total"]["value"]
            ms_ = r["hits"]["max_score"]
            if ms_ is not None and (max_score is None or ms_ > max_score):
                max_score = ms_
        all_hits = merge_hit_rows(rows, body.get("sort"))
        resp = {
            "took": int((time.monotonic() - t0) * 1000),
            "timed_out": any(r.get("timed_out") for r in shard_resps),
            "hits": {"total": {"value": total, "relation": "eq"},
                     "max_score": max_score,
                     "hits": all_hits[from_: from_ + size]},
        }
        if aggs_json:
            resp["aggregations"] = reduce_aggs(
                aggs_json, [r.get("aggregation_partials") or {}
                            for r in shard_resps])
        if body.get("suggest"):
            resp["suggest"] = merge_suggest(
                [r.get("suggest") for r in shard_resps])
        return resp

    def msearch(self, bodies: list) -> list[dict]:
        """Batched multi-search over the node-local searcher (term-bag
        bodies share device programs — search/batch.py), routed through
        the unified engine entry."""
        from opensearch_tpu.search.engine import query_engine
        results = query_engine().msearch(self.searcher(), bodies)
        for r in results:
            r["_shards"] = {"total": self.num_shards,
                            "successful": self.num_shards,
                            "skipped": 0, "failed": 0}
        return results

    def count(self, query: Optional[dict] = None) -> int:
        from opensearch_tpu.search.engine import query_engine
        return query_engine().count(self.searcher(), query)

    def doc_count(self) -> int:
        return sum(e.doc_count() for e in self.shards)

    def stats(self) -> dict:
        from opensearch_tpu.indices.request_cache import request_cache
        return {
            "docs": {"count": self.doc_count()},
            "shards": {"total": self.num_shards},
            "segments": {"count": sum(len(e.segments) for e in self.shards)},
            "request_cache": request_cache().stats_for_index(self.name),
        }

    def put_mapping(self, mapping: dict):
        self._check_write_block()   # schema must match the snapshot
        self.mapper.merge(mapping)
        self.save_meta()
        # a mapping change can alter how cached requests would compile
        self._dirty()

    def get_mapping(self) -> dict:
        return {"mappings": self.mapper.to_mapping()}

    def get_settings(self) -> dict:
        return {"settings": {"index": {
            "number_of_shards": str(self.num_shards),
            "number_of_replicas": str(self.num_replicas),
            "uuid": self.uuid,
            "creation_date": str(self.creation_date),
        }}}

    def close(self):
        from opensearch_tpu.indices.request_cache import request_cache
        for engine in self.shards:
            engine.close()
        request_cache().invalidate_service(self.uuid)


class IndicesService:
    """Node-level registry (IndicesService.java analog) with on-disk
    metadata so indices survive restarts."""

    def __init__(self, data_path: str):
        self.data_path = data_path
        os.makedirs(data_path, exist_ok=True)
        self._lock = threading.RLock()
        self.indices: dict[str, IndexService] = {}
        self._deleting: set[str] = set()   # names mid remote-cleanup
        # alias -> {index_name: {"filter": ..., "is_write_index": bool}}
        # (cluster-state aliases; ref cluster/metadata/AliasMetadata)
        self.aliases: dict[str, dict[str, dict]] = {}
        # composable index templates (ref cluster/metadata/
        # ComposableIndexTemplate): name -> body
        self.templates: dict[str, dict] = {}
        # searchable-snapshot blob cache, a sibling of the index dirs
        # (the reference's node-level FileCache, ref node/Node.java)
        from opensearch_tpu.index.filecache import FileCache
        self.file_cache = FileCache(
            os.path.join(os.path.dirname(data_path) or data_path,
                         "filecache"))
        self._pending_mounts: list[str] = []
        # data streams: name -> {"timestamp_field", "generation",
        # "indices": [backing names]} (cluster/metadata/DataStream)
        self.data_streams: dict[str, dict] = {}
        # node-wide indexing-pressure admission (ShardIndexingPressure)
        from opensearch_tpu.common.indexing_pressure import IndexingPressure
        self.indexing_pressure = IndexingPressure(
            int(os.environ.get("OSTPU_INDEXING_PRESSURE_LIMIT",
                               64 << 20)))
        self._aliases_file = os.path.join(data_path, "_aliases.json")
        self._templates_file = os.path.join(data_path,
                                            "_index_templates.json")
        self._datastreams_file = os.path.join(data_path,
                                              "_data_streams.json")
        for path, attr in ((self._aliases_file, "aliases"),
                           (self._templates_file, "templates"),
                           (self._datastreams_file, "data_streams")):
            if os.path.exists(path):
                with open(path) as f:
                    setattr(self, attr, json.load(f))
        self._load()

    def _meta_path(self, name: str) -> str:
        return os.path.join(self.data_path, name, "index_meta.json")

    def _persist_meta(self, name: str, settings: dict, mappings: dict):
        tmp = self._meta_path(name) + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"settings": settings, "mappings": mappings}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._meta_path(name))

    def set_repo_resolver(self, resolver, mutex_fn=None):
        """Late-bound blob-repository lookup (the node wires it once the
        snapshot service exists); applied to every open index."""
        self._repo_resolver = resolver
        self._repo_mutex_fn = mutex_fn
        for svc in self.indices.values():
            svc.repo_resolver = resolver
            svc.repo_mutex_fn = mutex_fn
        # open mounted (remote_snapshot) indices deferred at boot, best
        # effort: a vanished repository leaves the mount closed rather
        # than failing node startup
        import logging
        pending, self._pending_mounts = self._pending_mounts, []
        for name in pending:
            try:
                with open(self._meta_path(name)) as f:
                    meta = json.load(f)
                with self._lock, \
                        self._mount_materialize(name, meta["settings"]):
                    self.indices[name] = IndexService(
                        name, os.path.join(self.data_path, name),
                        meta["settings"], meta.get("mappings"),
                        persist_meta=self._persist_meta)
            except Exception as e:   # noqa: BLE001 — keep node booting
                logging.getLogger("opensearch_tpu.indices").warning(
                    "could not reopen mounted index [%s]: %s", name, e)

    def _mount_materialize(self, name: str, settings: dict):
        """Context manager: fetch/link a mounted index's segment files
        from its backing repository through the node file cache, and PIN
        the whole blob set until the caller's engines have opened —
        without the pin, materializing shard N under a small cache
        budget evicts shard 1's blobs from under their symlinks before
        the engine reads them."""
        import contextlib

        mount = settings.get("remote_snapshot") or {}
        resolver = getattr(self, "_repo_resolver", None)
        if resolver is None:
            raise ValidationError(
                f"cannot open mounted index [{name}]: no repository "
                "service")
        repo = resolver(mount["repository"])
        index_path = os.path.join(self.data_path, name)
        shard_dirs, blobs = [], set()
        for shard in sorted(os.listdir(index_path)):
            shard_dir = os.path.join(index_path, shard)
            ref_path = os.path.join(shard_dir, "remote_ref.json")
            if os.path.isfile(ref_path):
                with open(ref_path) as f:
                    blobs.update(fm["blob"]
                                 for fm in json.load(f)["files"])
                shard_dirs.append(shard_dir)

        @contextlib.contextmanager
        def mount_ctx():
            with self.file_cache.pin(blobs):
                for sd in shard_dirs:
                    self.file_cache.materialize_shard(sd, repo)
                yield

        return mount_ctx()

    def _load(self):
        for name in sorted(os.listdir(self.data_path)):
            meta_path = self._meta_path(name)
            if os.path.exists(meta_path):
                with open(meta_path) as f:
                    meta = json.load(f)
                if meta.get("settings", {}).get("remote_snapshot"):
                    # mounted indices need the blob repository, wired
                    # later via set_repo_resolver — defer the open
                    self._pending_mounts.append(name)
                    continue
                svc = IndexService(
                    name, os.path.join(self.data_path, name),
                    meta.get("settings", {}), meta.get("mappings"),
                    persist_meta=self._persist_meta)
                svc.indexing_pressure = self.indexing_pressure
                self.indices[name] = svc

    @staticmethod
    def validate_name(name: str):
        """Reference rules (MetadataCreateIndexService.validateIndexName):
        lowercase, no reserved characters, must not start with _ - +,
        not '.'/'..', < 255 bytes.  Any unicode satisfying those is
        legal (e.g. CJK names)."""
        bad = (not name or name != name.lower() or name in (".", "..")
               or name[0] in "_-+"
               or any(c in _INDEX_NAME_FORBIDDEN for c in name)
               or len(name.encode("utf-8")) > 255)
        if bad:
            raise ValidationError(
                f"invalid index name [{name}]: must be lowercase, must "
                "not contain [\\/*?\"<>|, #:] or spaces, and must not "
                "start with [_-+]")

    def _register(self, name: str, settings: dict,
                  mappings: Optional[dict]) -> IndexService:
        """Shared open+persist+register step for create and restore
        (call with the registry lock held)."""
        if name in self.indices:
            raise IndexAlreadyExistsError(name)
        if name in self._deleting:
            raise IllegalArgumentError(
                f"index [{name}] is being deleted — retry shortly")
        self.validate_name(name)
        if "index" in settings:       # accept {"settings": {"index": {...}}}
            inner = settings.pop("index")
            settings.update(inner)
        path = os.path.join(self.data_path, name)
        os.makedirs(path, exist_ok=True)
        import contextlib
        mount_ctx = (self._mount_materialize(name, settings)
                     if settings.get("remote_snapshot")
                     else contextlib.nullcontext())
        with mount_ctx:     # pin blobs until the engines have loaded
            svc = IndexService(name, path, settings, mappings,
                               persist_meta=self._persist_meta)
        svc.repo_resolver = getattr(self, "_repo_resolver", None)
        svc.repo_mutex_fn = getattr(self, "_repo_mutex_fn", None)
        svc.indexing_pressure = self.indexing_pressure
        self._persist_meta(name, settings, mappings or {})
        self.indices[name] = svc
        return svc

    def create(self, name: str, body: Optional[dict] = None) -> IndexService:
        body = body or {}
        with self._lock:
            if name in self.aliases:
                raise IndexAlreadyExistsError(name)
            settings = dict(body.get("settings", {}))
            mappings = body.get("mappings")
            tmpl = self._template_for(name)
            if tmpl is not None:
                # template under, request over (composable V2 merge)
                t = tmpl.get("template") or {}
                settings = {**(t.get("settings") or {}), **settings}
                if t.get("mappings"):
                    merged = dict(t["mappings"].get("properties") or {})
                    merged.update((mappings or {}).get("properties") or {})
                    mappings = {**t["mappings"], **(mappings or {}),
                                "properties": merged}
            svc = self._register(name, settings, mappings)
            tmpl_aliases = ((tmpl or {}).get("template") or {}).get(
                "aliases", {})
            req_aliases = body.get("aliases") or {}
            for alias, meta in {**tmpl_aliases, **req_aliases}.items():
                self.aliases.setdefault(alias, {})[name] = meta or {}
            if tmpl_aliases or req_aliases:
                self._persist_json(self._aliases_file, self.aliases)
            return svc

    def open_restored(self, name: str, settings: dict,
                      mappings: Optional[dict]) -> IndexService:
        """Open an index whose shard directories a snapshot restore just
        materialized (RestoreService's post-copy open)."""
        with self._lock:
            return self._register(name, dict(settings), mappings)

    def get(self, name: str) -> IndexService:
        svc = self.indices.get(name)
        if svc is None:
            svc = self._alias_single(name)
            if svc is None:
                raise IndexNotFoundError(name)
        return svc

    def _alias_single(self, name: str):
        """Resolve an alias for a single-index op (get/mget): one target
        resolves, several is an error (TransportSingleShardAction)."""
        targets = self.aliases.get(name)
        if not targets:
            return None
        if len(targets) > 1:
            raise IllegalArgumentError(
                f"alias [{name}] has more than one index associated with "
                f"it [{', '.join(sorted(targets))}], can't execute a "
                "single index op")
        return self.indices.get(next(iter(targets)))

    auto_create = True          # action.auto_create_index (dynamic)

    def get_or_create(self, name: str) -> IndexService:
        """Auto-create on first write (action.auto_create_index default)."""
        with self._lock:
            if name in self.indices:
                return self.indices[name]
            if not self.auto_create:
                raise IndexNotFoundError(name)
            return self.create(name)

    def exists(self, name: str) -> bool:
        return name in self.indices

    def delete(self, name: str):
        with self._lock:
            svc = self.get(name)
            remote_repo = None
            try:
                remote_repo = svc._remote_repo()
            except Exception:      # noqa: BLE001 — best-effort cleanup
                pass
            svc.close()
            del self.indices[name]
            # drop the index from every alias (empty aliases disappear,
            # like cluster-state alias metadata on index deletion)
            changed = False
            for alias in list(self.aliases):
                if self.aliases[alias].pop(name, None) is not None:
                    changed = True
                    if not self.aliases[alias]:
                        del self.aliases[alias]
            if changed:
                self._persist_json(self._aliases_file, self.aliases)
            if remote_repo is not None:
                # block same-name recreation until the remote cleanup
                # finishes, or the trailing GC would destroy the NEW
                # index's fresh mirror.  EVERY exit path from here on
                # must discard the guard (see the outer try/finally).
                self._deleting.add(name)
            try:
                shutil.rmtree(os.path.join(self.data_path, name),
                              ignore_errors=True)
                # aliases pointing only at the deleted index vanish too
                changed = False
                for alias in list(self.aliases):
                    if name in self.aliases[alias]:
                        del self.aliases[alias][name]
                        if not self.aliases[alias]:
                            del self.aliases[alias]
                        changed = True
                if changed:
                    self._persist_json(self._aliases_file, self.aliases)
            except BaseException:
                self._deleting.discard(name)
                raise
        if remote_repo is not None:
            # OUTSIDE the registry lock (the scan + GC is blob-store
            # I/O), under the repo mutex so snapshot create/delete can't
            # interleave: the mirror dies with the index, blobs nothing
            # references anymore go with it (the GC consults BOTH
            # consumers of the shared space)
            try:
                from opensearch_tpu.snapshots.service import \
                    collect_referenced_blobs
                mutex = (self._repo_mutex_fn(remote_repo.name)
                         if getattr(self, "_repo_mutex_fn", None)
                         else None)
                if mutex is not None:
                    mutex.acquire()
                try:
                    remote_repo.store.container(
                        f"remote/{name}").delete_tree()
                    referenced = collect_referenced_blobs(remote_repo)
                    for blob in list(remote_repo.blobs.list_blobs()):
                        if blob not in referenced:
                            remote_repo.blobs.delete_blob(blob)
                finally:
                    if mutex is not None:
                        mutex.release()
            finally:
                with self._lock:
                    self._deleting.discard(name)

    def resolve(self, expr: str) -> list[IndexService]:
        """Index expression: name, alias, comma list, * / _all wildcards
        (aliases resolve like the reference's IndexNameExpressionResolver)."""
        return [svc for svc, _f in self.resolve_with_filters(expr)]

    def resolve_with_filters(self, expr: str) -> list[tuple]:
        """[(IndexService, alias_filter|None)]: an index reached ONLY
        through filtered aliases carries the (should-of) alias filters;
        any unfiltered route wins (the reference's alias-filter
        application in QueryShardContext)."""
        if expr in ("_all", "*", ""):
            return [(s, None) for s in self.indices.values()]
        acc: dict[str, list] = {}       # name -> [filters] | [None]
        order: list[str] = []

        def add(name, flt):
            if name not in acc:
                acc[name] = [flt]
                order.append(name)
            elif None in acc[name] or flt is None:
                acc[name] = [None]
            else:
                acc[name].append(flt)

        def add_alias(alias):
            for n, meta in self.aliases[alias].items():
                if n in self.indices:
                    add(n, meta.get("filter"))

        for part in expr.split(","):
            if "*" in part:
                rx = re.compile("^" + re.escape(part).replace(r"\*", ".*")
                                + "$")
                for n in self.indices:
                    if rx.match(n):
                        add(n, None)
                for alias in self.aliases:
                    if rx.match(alias):
                        add_alias(alias)
                for ds in self.data_streams:
                    if rx.match(ds):
                        for n in self.data_streams[ds]["indices"]:
                            add(n, None)
            elif part in self.aliases:
                add_alias(part)
            elif part in self.data_streams:
                # a data stream searches all its backing indices
                for n in self.data_streams[part]["indices"]:
                    add(n, None)
            else:
                add(self.get(part).name, None)
        out = []
        for name in order:
            filters = acc[name]
            if None in filters:
                flt = None
            elif len(filters) == 1:
                flt = filters[0]
            else:
                flt = {"bool": {"should": filters,
                                "minimum_should_match": 1}}
            out.append((self.indices[name], flt))
        return out

    # -- aliases -----------------------------------------------------------

    def _persist_json(self, path: str, obj):
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(obj, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def update_aliases(self, actions: list) -> dict:
        """POST /_aliases action list (IndicesAliasesRequest)."""
        with self._lock:
            staged = {a: dict(t) for a, t in self.aliases.items()}
            for entry in actions or []:
                if not isinstance(entry, dict) or len(entry) != 1:
                    raise ValidationError(
                        "alias action must be one of add/remove/"
                        "remove_index")
                ((op, body),) = entry.items()
                if op == "remove_index":
                    raise ValidationError(
                        "[remove_index] is not supported")
                if op not in ("add", "remove"):
                    raise ValidationError(f"unknown alias action [{op}]")
                if not isinstance(body, dict):
                    raise ValidationError(
                        f"alias action [{op}] requires an object body")
                if body.get("routing") is not None:
                    raise ValidationError(
                        "alias [routing] is not supported")
                indices = body.get("indices") or [body.get("index")]
                names = body.get("aliases") or [body.get("alias")]
                if not all(indices) or not all(names):
                    raise ValidationError(
                        f"alias action [{op}] requires [index] and "
                        "[alias]")
                resolved = []
                for ix in indices:
                    resolved.extend(s.name for s in self.resolve(ix)
                                    if s.name in self.indices)
                for alias in names:
                    if alias in self.indices:
                        raise ValidationError(
                            f"an index named [{alias}] already exists")
                    for ix in resolved:
                        if op == "add":
                            meta = {}
                            if body.get("filter") is not None:
                                meta["filter"] = body["filter"]
                            if body.get("is_write_index"):
                                meta["is_write_index"] = True
                            staged.setdefault(alias, {})[ix] = meta
                        else:
                            staged.get(alias, {}).pop(ix, None)
            self.aliases = {a: t for a, t in staged.items() if t}
            self._persist_json(self._aliases_file, self.aliases)
        return {"acknowledged": True}

    def get_aliases(self, index: Optional[str] = None,
                    name: Optional[str] = None) -> dict:
        """GET /_alias family response shape: {index: {aliases: {...}}}."""
        out: dict[str, dict] = {}
        for alias, targets in self.aliases.items():
            if name is not None and not re.match(
                    "^" + re.escape(name).replace(r"\*", ".*") + "$",
                    alias):
                continue
            for ix, meta in targets.items():
                if index is not None and ix != index:
                    continue
                rendered = dict(meta or {})
                # a bare [routing] renders as both index_routing and
                # search_routing (AliasMetadata's xcontent shape)
                routing = rendered.pop("routing", None)
                if routing is not None:
                    rendered.setdefault("index_routing", routing)
                    rendered.setdefault("search_routing", routing)
                out.setdefault(ix, {"aliases": {}})["aliases"][alias] = \
                    rendered
        if name is not None and not out:
            raise ResourceNotFoundError(f"alias [{name}] missing")
        return out

    def write_index_for(self, alias: str) -> "IndexService":
        """Write resolution: an alias works for writes when it points at
        one index or names an explicit write index; a data stream always
        writes to its newest backing index."""
        if alias in self.data_streams:
            return self.data_stream_write_index(alias)
        targets = self.aliases.get(alias)
        if targets is None:
            return self.get_or_create(alias)
        writers = [ix for ix, meta in targets.items()
                   if meta.get("is_write_index")]
        if len(targets) == 1:
            return self.get(next(iter(targets)))
        if len(writers) == 1:
            return self.get(writers[0])
        raise IllegalArgumentError(
            f"no write index is defined for alias [{alias}]. The write "
            "index may be explicitly disabled using is_write_index=false "
            "or the alias points to multiple indices without one being "
            "designated as a write index")

    # -- index templates ---------------------------------------------------

    def put_template(self, name: str, body: dict) -> dict:
        patterns = body.get("index_patterns")
        if not patterns:
            raise ValidationError(
                "index template requires [index_patterns]")
        with self._lock:
            self.templates[name] = body
            self._persist_json(self._templates_file, self.templates)
        return {"acknowledged": True}

    def get_template(self, name: Optional[str] = None) -> dict:
        if name is None:
            items = sorted(self.templates.items())
        else:
            items = [(n, t) for n, t in sorted(self.templates.items())
                     if re.match("^" + re.escape(name)
                                 .replace(r"\*", ".*") + "$", n)]
            if not items and "*" not in name:
                raise ResourceNotFoundError(
                    f"index template matching [{name}] not found")
        return {"index_templates": [
            {"name": n, "index_template": t} for n, t in items]}

    def delete_template(self, name: str) -> dict:
        with self._lock:
            if name not in self.templates:
                raise ResourceNotFoundError(
                    f"index template [{name}] missing")
            del self.templates[name]
            self._persist_json(self._templates_file, self.templates)
        return {"acknowledged": True}

    # -- rollover / resize / data streams ---------------------------------

    @staticmethod
    def _next_rollover_name(name: str) -> str:
        """<base>-000001 -> <base>-000002; no numeric suffix appends one
        (MetadataRolloverService.generateRolloverIndexName)."""
        m = re.match(r"^(.*)-(\d+)$", name)
        if m:
            n = int(m.group(2)) + 1
            return f"{m.group(1)}-{n:0{max(6, len(m.group(2)))}d}"
        return f"{name}-000001"

    def _rollover_conditions_met(self, svc: IndexService,
                                 conditions: dict) -> dict:
        """Evaluate max_docs / max_age / max_size against the write
        index (RolloverRequest conditions)."""
        results = {}
        for cond, want in (conditions or {}).items():
            if cond == "max_docs":
                results["[max_docs: %s]" % want] = \
                    svc.doc_count() >= int(want)
            elif cond == "max_age":
                from opensearch_tpu.common.settings import parse_time
                # creation_date is a wall timestamp, so the age
                # comparison must stay in the same clock domain
                age_s = time.time() - svc.creation_date / 1000.0  # wall-clock
                results["[max_age: %s]" % want] = \
                    age_s >= parse_time(want)
            elif cond == "max_size":
                from opensearch_tpu.common.settings import parse_bytes
                size = sum(
                    sum(len(b) for b in seg.sources)
                    for e in svc.shards
                    for seg in e.acquire_searcher().segments)
                results["[max_size: %s]" % want] = \
                    size >= parse_bytes(want)
            else:
                raise IllegalArgumentError(
                    f"unknown rollover condition [{cond}]")
        return results

    def rollover(self, target: str, body: Optional[dict] = None,
                 dry_run: bool = False) -> dict:
        """Roll a write alias or data stream over to a fresh index
        (action/admin/indices/rollover/MetadataRolloverService)."""
        body = body or {}
        with self._lock:
            if target in self.data_streams:
                return self._rollover_data_stream(target, body, dry_run)
            targets = self.aliases.get(target)
            if not targets:
                raise IllegalArgumentError(
                    f"rollover target [{target}] is not an alias or "
                    "data stream")
            writers = [n for n, m in targets.items()
                       if m.get("is_write_index")]
            if len(targets) == 1:
                old = next(iter(targets))
            elif len(writers) == 1:
                old = writers[0]
            else:
                raise IllegalArgumentError(
                    f"rollover target [{target}] does not point to a "
                    "single write index")
            new = body.get("new_index") or self._next_rollover_name(old)
            conds = self._rollover_conditions_met(
                self.indices[old], body.get("conditions") or {})
            rolled = all(conds.values()) if conds else True
            out = {"acknowledged": rolled and not dry_run,
                   "shards_acknowledged": rolled and not dry_run,
                   "old_index": old, "new_index": new,
                   "rolled_over": rolled and not dry_run,
                   "dry_run": dry_run, "conditions": conds}
            if dry_run or not rolled:
                return out
            self.create(new, {k: v for k, v in body.items()
                              if k in ("settings", "mappings",
                                       "aliases")})
            meta = dict(targets.get(old) or {})
            meta["is_write_index"] = False
            self.aliases[target][old] = meta
            self.aliases[target][new] = {"is_write_index": True}
            self._persist_json(self._aliases_file, self.aliases)
            return out

    def resize(self, source: str, target: str, mode: str,
               body: Optional[dict] = None) -> dict:
        """shrink / split / clone: create ``target`` with the new shard
        count and re-bucket every live doc by the target routing (the
        reference relinks Lucene segments —
        action/admin/indices/shrink/TransportResizeAction; the array
        engine re-routes sources instead, same observable result)."""
        body = body or {}
        with self._lock:
            svc = self.get(source)
            if target in self.indices or target in self.aliases:
                raise IndexAlreadyExistsError(target)
            blocked = svc.index_setting(
                "blocks.write",
                (svc.settings.get("blocks") or {}).get("write", False))
            if str(blocked).lower() != "true":
                raise IllegalArgumentError(
                    f"index [{source}] must block writes to resize "
                    "(set index.blocks.write: true)")
            src_shards = svc.num_shards
            settings = dict(body.get("settings") or {})
            tgt_shards = int(settings.get(
                "number_of_shards",
                settings.get("index.number_of_shards",
                             1 if mode == "shrink" else
                             src_shards * 2 if mode == "split"
                             else src_shards)))
            if mode == "shrink" and src_shards % tgt_shards != 0:
                raise IllegalArgumentError(
                    f"the number of source shards [{src_shards}] must be "
                    f"a multiple of [{tgt_shards}]")
            if mode == "split" and tgt_shards % src_shards != 0:
                raise IllegalArgumentError(
                    f"the number of target shards [{tgt_shards}] must be "
                    f"a multiple of the source shards [{src_shards}]")
            if mode == "clone" and tgt_shards != src_shards:
                raise IllegalArgumentError(
                    "clone must keep the source's number of shards")
            settings["number_of_shards"] = tgt_shards
            settings.pop("index.number_of_shards", None)
            settings.pop("blocks", None)
            new_svc = self.create(target, {
                "settings": settings,
                "mappings": svc.get_mapping().get("mappings"),
                "aliases": body.get("aliases") or {}})
        # copy OUTSIDE the registry lock: doc-by-doc re-route.  Refresh
        # first — the copy reads segments, and unrefreshed hot-buffer
        # docs would silently miss the target otherwise
        svc.refresh()
        copied = 0
        for engine in svc.shards:
            searcher = engine.acquire_searcher()
            for seg in searcher.segments:
                for local in range(seg.n_docs):
                    if not seg.live[local]:
                        continue
                    new_svc.index_doc(seg.doc_ids[local],
                                      seg.source(local),
                                      routing=seg.routings.get(local))
                    copied += 1
        new_svc.refresh()
        return {"acknowledged": True, "shards_acknowledged": True,
                "index": target, "copied_docs": copied}

    # -- data streams ------------------------------------------------------

    def create_data_stream(self, name: str) -> dict:
        """A data stream needs a matching template with a [data_stream]
        section; its first backing index is .ds-<name>-000001
        (MetadataCreateDataStreamService)."""
        with self._lock:
            if name in self.data_streams:
                raise ResourceAlreadyExistsError(
                    f"data_stream [{name}] already exists")
            tmpl = self._template_for(name)
            if tmpl is None or "data_stream" not in tmpl:
                raise IllegalArgumentError(
                    f"no matching index template with a data_stream "
                    f"definition for [{name}]")
            ts_field = ((tmpl.get("data_stream") or {}).get(
                "timestamp_field") or {}).get("name", "@timestamp")
            backing = f".ds-{name}-000001"
            self.create(backing, {
                "mappings": {"properties": {ts_field: {"type": "date"}}}})
            self.data_streams[name] = {"timestamp_field": ts_field,
                                       "generation": 1,
                                       "indices": [backing]}
            self._persist_json(self._datastreams_file, self.data_streams)
            return {"acknowledged": True}

    def _rollover_data_stream(self, name: str, body: dict,
                              dry_run: bool) -> dict:
        ds = self.data_streams[name]
        old = ds["indices"][-1]
        conds = self._rollover_conditions_met(
            self.indices[old], (body or {}).get("conditions") or {})
        rolled = all(conds.values()) if conds else True
        gen = ds["generation"] + 1
        new = f".ds-{name}-{gen:06d}"
        out = {"acknowledged": rolled and not dry_run,
               "old_index": old, "new_index": new,
               "rolled_over": rolled and not dry_run,
               "dry_run": dry_run, "conditions": conds}
        if dry_run or not rolled:
            return out
        self.create(new, {"mappings": {"properties": {
            ds["timestamp_field"]: {"type": "date"}}}})
        ds["generation"] = gen
        ds["indices"].append(new)
        self._persist_json(self._datastreams_file, self.data_streams)
        return out

    def get_data_streams(self, name: Optional[str] = None) -> dict:
        with self._lock:
            items = []
            for n, ds in sorted(self.data_streams.items()):
                if name and name != n and not re.match(
                        "^" + re.escape(name).replace(r"\*", ".*") + "$",
                        n):
                    continue
                items.append({
                    "name": n,
                    "timestamp_field": {"name": ds["timestamp_field"]},
                    "indices": [{"index_name": i} for i in ds["indices"]],
                    "generation": ds["generation"],
                    "status": "GREEN",
                })
            return {"data_streams": items}

    def delete_data_stream(self, name: str) -> dict:
        with self._lock:
            ds = self.data_streams.get(name)
            if ds is None:
                raise ResourceNotFoundError(
                    f"data_stream [{name}] not found")
            for backing in ds["indices"]:
                if backing in self.indices:
                    self.delete(backing)
            del self.data_streams[name]
            self._persist_json(self._datastreams_file, self.data_streams)
            return {"acknowledged": True}

    def data_stream_write_index(self, name: str) -> "IndexService":
        ds = self.data_streams[name]
        return self.get(ds["indices"][-1])

    def _template_for(self, name: str) -> Optional[dict]:
        """Highest-priority template whose pattern matches ``name``."""
        best = None
        best_prio = -1
        for t in self.templates.values():
            for p in t.get("index_patterns") or []:
                if re.match("^" + re.escape(p).replace(r"\*", ".*") + "$",
                            name):
                    prio = int(t.get("priority", 0))
                    if prio > best_prio:
                        best, best_prio = t, prio
        return best

    def close(self):
        for svc in self.indices.values():
            svc.close()
