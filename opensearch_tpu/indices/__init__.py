from opensearch_tpu.indices.service import IndexService, IndicesService  # noqa: F401
