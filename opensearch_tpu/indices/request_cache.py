"""Shard-level search request cache.

Analog of the reference's ``indices/IndicesRequestCache.java``: a
node-level cache of shard query-phase results keyed on (shard owner,
reader generation, canonicalized request body).  Keying on the reader
generation makes staleness structurally impossible — a refresh, mapping
change or checkpoint install bumps the generation and every old key
stops matching; ``IndexService._dirty`` additionally drops the dead
generation's entries eagerly so memory follows visibility.

Values are the JSON-serialized response bytes, not the response object:

- a hit deserializes a FRESH dict, so per-request coordinator mutations
  (``_shards`` rewrites, ``track_total_hits`` folding) can never poison
  the cached copy, and
- the round-trip guarantees a hit renders byte-identical to the miss
  that populated it (including ``took``) — the property the tests pin.

Residency is bounded by the dynamic ``indices.requests.cache.size``
node setting and charged against the ``request`` circuit breaker via
the underlying ``common/cache.py`` primitive.  Responses that are not
JSON-serializable (device partials) or that timed out (partial results)
are computed but never admitted.

Process-global singleton like ``breaker_service()``: multi-node-in-one-
process tests share it, which is safe because every key carries the
owning IndexService's uuid (two nodes' copies of the same shard never
collide) — per-node attribution in those tests reads the execution
counters instead.
"""

from __future__ import annotations

import json
import threading
from typing import Callable, Optional

from opensearch_tpu.common.cache import EVICTED, Cache

DEFAULT_MAX_BYTES = 64 << 20          # indices.requests.cache.size default


class IndicesRequestCache:
    def __init__(self, max_bytes: int = DEFAULT_MAX_BYTES):
        self._lock = threading.Lock()
        # index name -> {"hit_count", "miss_count", "evictions"}
        self._per_index: dict[str, dict] = {}
        self._cache = Cache(
            "request_cache", max_weight=int(max_bytes),
            weigher=self._weigh, breaker="request",
            removal_listener=self._on_remove)

    # key = (svc_uuid, shard_key, reader_gen, body_key)
    # value = (index_name, payload_bytes)

    @staticmethod
    def _weigh(key, value) -> int:
        return len(key[3]) + len(value[1]) + 64

    def _on_remove(self, key, value, reason: str) -> None:
        if reason == EVICTED:
            with self._lock:
                self._index_stats(value[0])["evictions"] += 1

    def _index_stats(self, index: str) -> dict:
        return self._per_index.setdefault(
            index, {"hit_count": 0, "miss_count": 0, "evictions": 0})

    @staticmethod
    def request_key(body: dict) -> str:
        """Canonical request identity: key order in the body must not
        change the cache key (raises TypeError for unserializable
        bodies — those are uncacheable anyway)."""
        return json.dumps(body or {}, sort_keys=True,
                          separators=(",", ":"))

    # -- the read path -----------------------------------------------------

    def get_or_compute(self, *, index: str, svc_uuid: str, shard_key: str,
                       reader_gen: int, body: dict,
                       compute: Callable[[], dict]) -> tuple[dict, bool]:
        """Serve ``compute()``'s response through the cache; returns
        (response, was_hit).  Uncacheable requests/responses fall
        through to a plain compute."""
        try:
            bkey = self.request_key(body)
        except (TypeError, ValueError):
            return compute(), False
        key = (svc_uuid, str(shard_key), int(reader_gen), bkey)
        cached = self._cache.get(key)
        if cached is not None:
            with self._lock:
                self._index_stats(index)["hit_count"] += 1
            return json.loads(cached[1]), True
        resp = compute()
        with self._lock:
            self._index_stats(index)["miss_count"] += 1
        # partial results must never be replayed as complete ones
        if resp.get("timed_out") or \
                (resp.get("resp") or {}).get("timed_out"):
            return resp, False
        try:
            payload = json.dumps(resp, separators=(",", ":")).encode()
        except (TypeError, ValueError):
            return resp, False           # device partials et al.
        self._cache.put(key, (index, payload))
        return resp, False

    # -- invalidation ------------------------------------------------------

    def invalidate_service(self, svc_uuid: str) -> int:
        """Drop every entry owned by one IndexService instance (refresh /
        mapping change / shard set change / close)."""
        return self._cache.invalidate_if(lambda k, v: k[0] == svc_uuid)

    def clear(self, index: Optional[str] = None) -> int:
        """``POST /<index>/_cache/clear``: drop entries (all, or one
        index's) and reset that scope's counters."""
        if index is None:
            n = self._cache.invalidate_if(lambda k, v: True)
            with self._lock:
                self._per_index.clear()
            return n
        n = self._cache.invalidate_if(lambda k, v: v[0] == index)
        with self._lock:
            self._per_index.pop(index, None)
        return n

    def set_max_bytes(self, max_bytes: int) -> None:
        self._cache.set_max_weight(int(max_bytes))

    # -- stats -------------------------------------------------------------

    def stats(self) -> dict:
        """Node-level ``_nodes/stats`` section."""
        with self._lock:
            hits = sum(s["hit_count"] for s in self._per_index.values())
            misses = sum(s["miss_count"]
                         for s in self._per_index.values())
        c = self._cache.stats()
        return {"memory_size_in_bytes": c["memory_size_in_bytes"],
                "entries": c["entries"],
                "hit_count": hits, "miss_count": misses,
                "evictions": c["evictions"]}

    def stats_for_index(self, index: str) -> dict:
        """Per-index ``_stats`` section."""
        memory = sum(w for _k, v, w in self._cache.entries()
                     if v[0] == index)
        entries = sum(1 for _k, v, _w in self._cache.entries()
                      if v[0] == index)
        with self._lock:
            counts = dict(self._per_index.get(
                index, {"hit_count": 0, "miss_count": 0, "evictions": 0}))
        return {"memory_size_in_bytes": memory, "entries": entries,
                **counts}


# node-global default instance (the breaker_service() singleton pattern)
_default = IndicesRequestCache()


def request_cache() -> IndicesRequestCache:
    return _default


def install(cache: IndicesRequestCache) -> None:
    global _default
    _default = cache
