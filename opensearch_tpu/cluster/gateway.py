"""Durable per-node coordination state — the gateway analog.

The reference persists every ACCEPTED cluster state and the current
coordination term into a node-local Lucene index
(ref gateway/PersistedClusterStateService.java:137, IndexWriter at :222)
because the protocol's safety arguments assume votes and accepted states
survive restarts: a node that voted in term T must never vote again in T
after a crash, and a committed state must remain present (as *accepted*)
on a majority.  Without this, a full-cluster restart resets terms to 0
and voids every primary-term fencing guarantee built on top.

Here the durable pieces are three JSON files under ``<data>/_state``,
each written atomically (tmp + fsync + rename — the same discipline as
the engine's commit point):

- ``terms.json``     — current_term + last_join_term (the vote)
- ``accepted.json``  — the full last-accepted cluster state payload
- ``commit.json``    — (term, version) marker of the last commit

JSON instead of a Lucene index is deliberate: cluster states here are
small dict payloads, and the atomic-rename file is the idiomatic host
equivalent; nothing about it touches the device path.
"""

from __future__ import annotations

import json
import os
from typing import Optional


class GatewayStateStore:
    TERMS = "terms.json"
    ACCEPTED = "accepted.json"
    COMMIT = "commit.json"

    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)

    # -- io ----------------------------------------------------------------

    def _write(self, name: str, obj: dict):
        tmp = os.path.join(self.path, name + ".tmp")
        with open(tmp, "w") as f:
            json.dump(obj, f, separators=(",", ":"))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.path, name))

    def _read(self, name: str) -> Optional[dict]:
        p = os.path.join(self.path, name)
        if not os.path.exists(p):
            return None
        try:
            with open(p) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            # a torn write can only affect the .tmp (rename is atomic);
            # an unreadable final file means manual tampering — treat as
            # absent rather than refusing to boot
            return None

    # -- writes on the coordination hot path -------------------------------

    def save_terms(self, current_term: int, last_join_term: int):
        self._write(self.TERMS, {"current_term": int(current_term),
                                 "last_join_term": int(last_join_term)})

    def save_accepted(self, payload: dict):
        self._write(self.ACCEPTED, payload)

    def save_commit(self, term: int, version: int):
        self._write(self.COMMIT, {"term": int(term),
                                  "version": int(version)})

    # -- restart ----------------------------------------------------------

    def load(self) -> dict:
        """{"current_term", "last_join_term", "accepted": payload|None,
        "commit": (term, version)|None} — all zeros/None on first boot."""
        terms = self._read(self.TERMS) or {}
        commit = self._read(self.COMMIT)
        return {
            "current_term": int(terms.get("current_term", 0)),
            "last_join_term": int(terms.get("last_join_term", 0)),
            "accepted": self._read(self.ACCEPTED),
            "commit": ((int(commit["term"]), int(commit["version"]))
                       if commit else None),
        }
