"""Node fault detection: the leader checks its followers, followers
check their leader.

Analog of ``cluster/coordination/FollowersChecker.java`` (:48 — the
``internal:coordination/fault_detection/follower_check`` action, its
interval/timeout/retry settings) and ``LeaderChecker.java`` (:63, the
``leader_check`` twin).  Both checkers ping over the ordinary
TransportService; after ``retries`` CONSECUTIVE failures the follower
checker hands the dead node to the coordinator (which publishes a state
update removing it — replica promotion rides on ``allocate_shards``),
and the leader checker demotes the local node to candidate and triggers
an election.

The failure counters live in a dict SHARED with the coordinator
(``Coordinator._check_failures``) so election gating
(``_leader_alive``) keeps seeing the same evidence the checkers do.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from opensearch_tpu.common.errors import OpenSearchTpuError

FOLLOWER_CHECK = "internal:coordination/fault_detection/follower_check"
LEADER_CHECK = "internal:coordination/fault_detection/leader_check"


class FaultDetectionSettings:
    """The three knobs both checkers share (the reference's
    ``cluster.fault_detection.{follower,leader}_check.{interval,timeout,
    retry_count}`` settings, collapsed to one group at this fidelity)."""

    def __init__(self, interval: float = 1.0, timeout: float = 2.0,
                 retries: int = 3):
        self.interval = float(interval)
        self.timeout = float(timeout)
        self.retries = int(retries)

    @staticmethod
    def from_settings(s: Optional[dict]) -> "FaultDetectionSettings":
        s = s or {}
        return FaultDetectionSettings(
            interval=float(s.get("cluster.fault_detection.check.interval",
                                 1.0)),
            timeout=float(s.get("cluster.fault_detection.check.timeout",
                                2.0)),
            retries=int(s.get("cluster.fault_detection.check.retry_count",
                              3)))


class FollowerChecker:
    """Leader side: ping every node in the committed state each round;
    a node that fails ``retries`` consecutive rounds (unreachable, wrong
    term, or applying states too slowly — the LagDetector fold-in) is
    reported to ``on_node_failure``."""

    def __init__(self, transport, node_id: str,
                 settings: FaultDetectionSettings,
                 failures: dict,
                 on_node_failure: Callable[[str, str], None],
                 load_provider: Optional[Callable[[], dict]] = None,
                 on_node_load: Optional[Callable[[str, dict],
                                                 None]] = None):
        self.transport = transport
        self.node_id = node_id
        self.settings = settings
        self._failures = failures        # peer -> consecutive failures
        self.on_node_failure = on_node_failure
        # adaptive-selection piggyback: pings double as a freshness
        # fallback for per-node load (duress flag, queue depth) so the
        # coordinator's ResponseCollectorService stays current even when
        # no searches are flowing to a node
        self.load_provider = load_provider
        self.on_node_load = on_node_load
        self._lock = threading.Lock()

    def handle_check(self, payload: dict, *, term: int,
                     is_follower: bool, applied_version: int) -> dict:
        """Follower side of the ping: am I following you in this term?
        The applied version rides along for lag detection, the local
        load snapshot for adaptive replica selection."""
        out = {"ok": payload.get("term") == term and is_follower,
               "version": applied_version}
        if self.load_provider is not None:
            try:
                out["load"] = self.load_provider()
            except Exception:  # noqa: BLE001 — load is best-effort
                pass
        return out

    def check_round(self, state, term: int) -> list:
        """One round over the follower set; returns nodes failed THIS
        round (after their retry budget ran out)."""
        from opensearch_tpu.common.telemetry import metrics

        dead = []
        for peer in [n for n in state.nodes if n != self.node_id]:
            lagging = unhealthy = False
            try:
                r = self.transport.send_request(
                    peer, FOLLOWER_CHECK, {"term": term},
                    timeout=self.settings.timeout)
                ok = r.get("ok")
                if self.on_node_load is not None and r.get("load"):
                    self.on_node_load(peer, r["load"])
                # LagDetector (coordination/LagDetector.java): a
                # follower that acks checks but never APPLIES the
                # published state is as gone as a dead one — it would
                # serve stale reads forever
                lagging = bool(ok) and (int(r.get("version",
                                                  state.version))
                                        < state.version)
                # FsHealth piggyback (the reference's
                # NodeHealthCheckFailureException on follower checks): a
                # node whose disk stopped taking writes answers pings
                # fine but cannot durably hold data — after the same
                # retry budget it leaves the cluster like a dead one
                unhealthy = bool(ok) and (
                    (r.get("load") or {}).get("fs_healthy") is False)
            except OpenSearchTpuError:
                ok = False
            with self._lock:
                if ok and not lagging and not unhealthy:
                    self._failures.pop(peer, None)
                    continue
                n = self._failures.get(peer, 0) + 1
                self._failures[peer] = n
                exhausted = n >= self.settings.retries
                if exhausted:
                    self._failures.pop(peer, None)
            if exhausted:
                metrics().counter("fault_detection.follower.failed").inc()
                reason = ("unhealthy" if unhealthy
                          else "lagging" if lagging else "disconnected")
                dead.append(peer)
                self.on_node_failure(peer, reason)
        return dead


class LeaderChecker:
    """Follower side: ping the elected leader each round; after
    ``retries`` consecutive failures call ``on_leader_failure`` (the
    coordinator demotes to candidate and re-elects)."""

    def __init__(self, transport, node_id: str,
                 settings: FaultDetectionSettings,
                 failures: dict,
                 on_leader_failure: Callable[[str], None],
                 load_provider: Optional[Callable[[], dict]] = None,
                 on_node_load: Optional[Callable[[str, dict],
                                                 None]] = None):
        self.transport = transport
        self.node_id = node_id
        self.settings = settings
        self._failures = failures
        self.on_leader_failure = on_leader_failure
        self.load_provider = load_provider
        self.on_node_load = on_node_load
        self._lock = threading.Lock()

    def handle_check(self, payload: dict, *, is_leader: bool,
                     term: int) -> dict:
        out = {"leader": is_leader, "term": term}
        if self.load_provider is not None:
            try:
                out["load"] = self.load_provider()
            except Exception:  # noqa: BLE001 — load is best-effort
                pass
        return out

    def check_round(self, leader: str) -> bool:
        """One ping; returns True when the leader just got declared
        dead (the caller re-elects)."""
        from opensearch_tpu.common.telemetry import metrics

        try:
            r = self.transport.send_request(
                leader, LEADER_CHECK, {}, timeout=self.settings.timeout)
            ok = r.get("leader")
            if self.on_node_load is not None and r.get("load"):
                self.on_node_load(leader, r["load"])
        except OpenSearchTpuError:
            ok = False
        with self._lock:
            if ok:
                self._failures.pop(leader, None)
                return False
            n = self._failures.get(leader, 0) + 1
            self._failures[leader] = n
            if n < self.settings.retries:
                return False
        metrics().counter("fault_detection.leader.failed").inc()
        self.on_leader_failure(leader)
        return True
