from opensearch_tpu.cluster.state import ClusterState  # noqa: F401
from opensearch_tpu.cluster.coordination import Coordinator  # noqa: F401
