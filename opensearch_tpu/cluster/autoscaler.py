"""QoS-driven searcher autoscaling: the elasticity control loop.

The cluster manager owns the routing table and (through PR 15) reacts
to *failure*; this module makes it react to *load*.  The
``SearcherAutoscaler`` runs on the elected leader and closes the loop
from QoS evidence (admission occupancy, measured Retry-After EWMAs —
the same signals the ``QosController`` adapts knobs from) to fleet
mutation: provision a search-only replica node when the evidence stays
hot past a dwell window, retire one through a drain protocol when it
stays cold.  Hysteresis (separate hot/cold thresholds), the dwell
window, and per-direction cooldowns keep the fleet from flapping.

Drain protocol (``retire_searcher``) — the ONLY sanctioned way to take
a searcher out of service; both the autoscaler and the soak's
``kill_searcher`` directive route through it:

1. Commit a state update marking the node ``draining`` — allocation
   excludes draining nodes from the searcher pool, so the same
   committed state removes the victim from every ``search_replicas`` /
   ``search_in_sync`` set.  No new scatters route to it.
2. Tombstone the victim in the coordinator-side C3 collector so the
   adaptive selector stops considering it immediately (before the
   state round-trips).
3. Wait for in-flight shard RPCs to complete (collector ``outstanding``
   drains to zero) and FileCache pins to release.
4. Stop the node, then remove it from the cluster state entirely.

``cluster.autoscale.drain_timeout_s`` bounds step 3: past the deadline
the retirement escalates to a hard kill and the partial-results path
absorbs any straggler responses.

Crash safety: every fleet mutation is a single committed state update
(node + search-slot settings + allocation in one publish), so the
cluster state never contains a half-admitted node.  A leader that dies
after committing ``draining`` but before finishing the drain leaves a
durable marker; the next leader's ``run_once`` finds it and completes
the retirement (``resume_drain``).  A provisioned-but-never-committed
node is abandoned by the provisioning leader itself (the publish
raised), and never becomes cluster state.

Every decision appends to the QosController's audit ring (PR 14) with
its numeric evidence and files a flight-recorder capture.

Module globals below are dynamic-setting targets
(``cluster.autoscale.*``, registered in ``opensearch_tpu/node.py``);
per-instance attributes override them when set (the soak pins its own
thresholds without touching global knobs).
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Callable, Optional

from opensearch_tpu.common.errors import OpenSearchTpuError
from opensearch_tpu.cluster.state import allocate_shards, node_roles

# -- dynamic settings (cluster.autoscale.*) -------------------------------
AUTOSCALE_ENABLED = False
MIN_SEARCHERS = 1
MAX_SEARCHERS = 4
DWELL_S = 3.0
COOLDOWN_S = 10.0
DRAIN_TIMEOUT_S = 5.0

# -- decision thresholds (the hysteresis band) ----------------------------
HOT_OCCUPANCY = 0.75      # tenant-weighted occupancy at/above -> hot
COLD_OCCUPANCY = 0.10     # at/below (and retry quiet) -> cold
HOT_RETRY_AFTER_S = 2.0   # measured Retry-After EWMA at/above -> hot

#: node-id prefix for autoscaler-provisioned searchers; retirement
#: prefers these (LIFO) so operator-placed searchers survive churn
NODE_ID_PREFIX = "as"


def retire_searcher(coordinator, victim: str, *,
                    collector=None, node=None,
                    drain_timeout_s: Optional[float] = None,
                    poll_s: float = 0.005,
                    audit: Optional[Callable] = None,
                    rank: Optional[Callable] = None) -> dict:
    """Drain-safe searcher retirement (see module docstring, steps 1-4).

    ``collector`` is the leader's ResponseCollectorService (C3) — used
    both to tombstone the victim and as the in-flight-RPC drain
    barrier.  ``node`` is the victim's in-process node object when the
    caller can resolve it (soak / autoscaler provisioned it); ``None``
    skips the local stop (a real remote node stops itself on eviction).
    ``audit(knob, old, new, evidence)`` receives the retirement record.
    Returns ``{"node", "drained", "hard_kill", "drain_s"}``.
    """
    timeout = DRAIN_TIMEOUT_S if drain_timeout_s is None else \
        float(drain_timeout_s)
    t0 = time.monotonic()
    deadline = t0 + max(0.0, timeout)

    def mark_draining(state):
        info = state.nodes.get(victim)
        if info is None:
            return state
        if not info.get("draining"):
            nodes = dict(state.nodes)
            nodes[victim] = dict(info, draining=True)
            state = state.with_(nodes=nodes)
        # allocation sees the draining flag and vacates the victim's
        # search slots in this same committed update
        return allocate_shards(state, rank=rank)

    coordinator.submit_state_update(mark_draining)
    if collector is not None:
        collector.remove_node(victim)  # C3 tombstone: stop selecting NOW

    hard_kill = False

    def _wait(pred) -> bool:
        nonlocal hard_kill
        while not pred():
            if time.monotonic() >= deadline:
                hard_kill = True
                return False
            time.sleep(poll_s)  # deadline (drain_timeout_s hard-kill above)
        return True

    if collector is not None:
        _wait(lambda: collector.outstanding(victim) <= 0)
    fc = getattr(node, "file_cache", None)
    if fc is not None:
        _wait(lambda: fc.stats().get("pinned_entries", 0) == 0)
    if node is not None:
        node.stop()
    coordinator.remove_node(victim)
    out = {"node": victim, "drained": not hard_kill,
           "hard_kill": hard_kill,
           "drain_s": round(time.monotonic() - t0, 6)}
    if audit is not None:
        audit("autoscale.drain", "serving",
              "hard_killed" if hard_kill else "retired", dict(out))
    return out


class SearcherAutoscaler:
    """Leader-driven searcher fleet controller.

    Tick-driven like the QosController: ``maybe_tick()`` is called from
    the search hot path and self-paces on an injectable clock; no
    background thread, so soak runs stay deterministic.  All limits
    (``enabled``, ``min_searchers``, ...) are instance attributes that
    default to ``None`` meaning "use the module global" (the dynamic
    setting); the soak pins instance values directly.

    ``provision(node_id) -> info-dict|None`` must build AND start the
    new searcher node, returning its discovery info (``None`` for the
    default searcher info).  ``resolve(node_id) -> node|None`` maps ids
    to in-process node objects for drain/stop.  ``on_retired(node_id)``
    fires after a retirement or abandon so the harness can drop its
    references.  Without a provisioner, scale-up decisions are recorded
    as skipped — the controller never half-acts.
    """

    def __init__(self, coordinator, *, admission, collector=None,
                 qos=None, clock: Callable[[], float] = time.monotonic,
                 interval_s: float = 1.0,
                 provision: Optional[Callable] = None,
                 resolve: Optional[Callable] = None,
                 on_retired: Optional[Callable] = None):
        self.coordinator = coordinator
        self.admission = admission
        self.collector = collector
        self.qos = qos
        self.clock = clock
        self.interval_s = float(interval_s)
        self.provision = provision
        self.resolve = resolve
        self.on_retired = on_retired
        # None -> defer to the module global (dynamic setting)
        self.enabled: Optional[bool] = None
        self.min_searchers: Optional[int] = None
        self.max_searchers: Optional[int] = None
        self.dwell_s: Optional[float] = None
        self.cooldown_s: Optional[float] = None
        self.drain_timeout_s: Optional[float] = None
        self.hot_occupancy = HOT_OCCUPANCY
        self.cold_occupancy = COLD_OCCUPANCY
        self.hot_retry_after_s = HOT_RETRY_AFTER_S
        #: optional capacity link: admission max_concurrent tracks the
        #: fleet (= per_searcher * n_searchers) after each scale event
        self.concurrency_per_searcher: Optional[int] = None
        self._hot_since: Optional[float] = None
        self._cold_since: Optional[float] = None
        self._last_scale: Optional[float] = None
        self._last_tick: Optional[float] = None
        self._tick_lock = threading.Lock()
        self._run_lock = threading.Lock()
        self._stopped = False
        self.scale_ups = 0
        self.scale_downs = 0
        self.hard_kills = 0
        self.abandoned = 0
        self.ticks = 0
        self.last_decision: dict = {}

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._stopped = False
        self._hot_since = self._cold_since = None
        self._last_tick = None

    def stop(self) -> None:
        self._stopped = True

    # -- effective limits --------------------------------------------------

    def _on(self) -> bool:
        v = self.enabled
        return AUTOSCALE_ENABLED if v is None else bool(v)

    def _min(self) -> int:
        v = self.min_searchers
        return int(MIN_SEARCHERS if v is None else v)

    def _max(self) -> int:
        v = self.max_searchers
        return int(MAX_SEARCHERS if v is None else v)

    def _dwell(self) -> float:
        v = self.dwell_s
        return float(DWELL_S if v is None else v)

    def _cooldown(self) -> float:
        v = self.cooldown_s
        return float(COOLDOWN_S if v is None else v)

    def _drain_timeout(self) -> float:
        v = self.drain_timeout_s
        return float(DRAIN_TIMEOUT_S if v is None else v)

    # -- fleet view (rebuilt from cluster state every tick: a new
    # -- leader inherits decision state for free) --------------------------

    @staticmethod
    def _searchers(state) -> list:
        return sorted(n for n, info in state.nodes.items()
                      if "search" in node_roles(info)
                      and not (info or {}).get("draining"))

    @staticmethod
    def _draining(state) -> list:
        return sorted(n for n, info in state.nodes.items()
                      if (info or {}).get("draining"))

    def _next_id(self, state) -> str:
        for i in itertools.count():
            nid = f"{NODE_ID_PREFIX}{i}"
            if nid not in state.nodes:
                return nid
        raise AssertionError("unreachable")

    # -- evidence ----------------------------------------------------------

    def _evidence(self) -> dict:
        stats = self.admission.stats()
        occ = float(stats.get("occupancy") or 0.0)
        weighted = occ
        for label, row in sorted((stats.get("tenants") or {}).items()):
            cap = row.get("max_concurrent")
            if cap:
                weighted = max(weighted,
                               float(row.get("inflight", 0)) / float(cap))
        retry = float(stats.get("retry_after_s") or 0.0)
        hot = (weighted >= self.hot_occupancy
               or retry >= self.hot_retry_after_s)
        cold = (weighted <= self.cold_occupancy
                and retry < self.hot_retry_after_s)
        return {"occupancy": round(occ, 4),
                "weighted_occupancy": round(weighted, 4),
                "retry_after_s": round(retry, 4),
                "hot": hot, "cold": cold}

    # -- audit -------------------------------------------------------------

    def _audit(self, knob: str, old, new, evidence: dict) -> None:
        if self.qos is not None:
            self.qos.record_adaptation(knob, old, new, evidence)

    # -- ticking -----------------------------------------------------------

    def maybe_tick(self) -> Optional[dict]:
        """Self-paced tick for the search hot path: cheap when disabled
        or off-leader, at most one evaluation per ``interval_s``."""
        if self._stopped or not self._on():
            return None
        now = self.clock()
        with self._tick_lock:
            if (self._last_tick is not None
                    and now - self._last_tick < self.interval_s):
                return None
            self._last_tick = now
        try:
            return self.run_once()
        except OpenSearchTpuError:
            return None  # lost leadership mid-tick; next leader resumes

    def run_once(self) -> dict:
        """One deterministic control-loop evaluation.  Returns the
        decision record (also kept as ``last_decision``)."""
        if not self._run_lock.acquire(blocking=False):
            # a drain in progress ticks the search path re-entrantly;
            # never start a second actuation underneath it
            return {"action": "none", "reason": "tick_in_progress"}
        try:
            return self._run_once_locked()
        finally:
            self._run_lock.release()

    def _run_once_locked(self) -> dict:
        self.ticks += 1
        now = self.clock()
        if self._stopped or not self._on():
            self._hot_since = self._cold_since = None
            return self._done({"action": "none", "reason": "disabled"})
        if not self.coordinator.is_leader():
            self._hot_since = self._cold_since = None
            return self._done({"action": "none", "reason": "not_leader"})
        state = self.coordinator.state()
        draining = self._draining(state)
        if draining:
            # a previous leader committed the drain marker but never
            # finished: complete the retirement from durable state
            return self._done(self._resume_drain(state, draining[0]))
        searchers = self._searchers(state)
        n = len(searchers)
        ev = self._evidence()
        if ev["hot"] and n < self._max():
            self._cold_since = None
            if self._hot_since is None:
                self._hot_since = now
            dwelled = now - self._hot_since
            if dwelled >= self._dwell() and self._cooled(now):
                return self._done(self._scale_up(state, searchers, ev))
            return self._done({"action": "none", "reason": "dwell_up",
                               "dwell_s": round(dwelled, 4),
                               "evidence": ev})
        if ev["cold"] and n > self._min():
            self._hot_since = None
            if self._cold_since is None:
                self._cold_since = now
            dwelled = now - self._cold_since
            if dwelled >= self._dwell() and self._cooled(now):
                return self._done(self._scale_down(state, searchers, ev))
            return self._done({"action": "none", "reason": "dwell_down",
                               "dwell_s": round(dwelled, 4),
                               "evidence": ev})
        self._hot_since = self._cold_since = None
        return self._done({"action": "none", "reason": "steady",
                           "searchers": n, "evidence": ev})

    def _cooled(self, now: float) -> bool:
        # one cooldown clock for both directions: a scale event in
        # EITHER direction opens a quiet window, which is exactly the
        # anti-flap guard (up->down->up churn pays two cooldowns)
        return (self._last_scale is None
                or now - self._last_scale >= self._cooldown())

    def _done(self, decision: dict) -> dict:
        self.last_decision = decision
        return decision

    # -- actuation ---------------------------------------------------------

    def _scale_up(self, state, searchers: list, evidence: dict) -> dict:
        if self.provision is None:
            return {"action": "none", "reason": "no_provisioner",
                    "evidence": evidence}
        nid = self._next_id(state)
        info = self.provision(nid) or {
            "name": nid, "roles": ["search"], "master_eligible": False}
        n_after = len(searchers) + 1
        reconf = self.coordinator._reconfigure

        def admit(st):
            if nid in st.nodes:
                return st
            nodes = dict(st.nodes)
            nodes[nid] = dict(info)
            # search slots track the fleet: any index that opted into
            # the tier gets one slot per live searcher, so the new node
            # actually serves (and the drain path's min() shrinks it
            # back without a second update)
            indices = {}
            for name, meta in st.indices.items():
                settings = dict((meta or {}).get("settings") or {})
                if int(settings.get("number_of_search_replicas", 0)
                       or 0) > 0:
                    settings["number_of_search_replicas"] = n_after
                    meta = dict(meta, settings=settings)
                indices[name] = meta
            return allocate_shards(
                st.with_(nodes=nodes, indices=indices,
                         voting=reconf(nodes)),
                rank=getattr(self.coordinator, "rank_fn", None))

        try:
            self.coordinator.submit_state_update(admit)
        except OpenSearchTpuError as exc:
            return self._abandon(nid, evidence, str(exc))
        self.scale_ups += 1
        self._last_scale = self.clock()
        self._hot_since = None
        self._sync_concurrency(n_after, evidence)
        self._audit("autoscale.searchers", len(searchers), n_after,
                    dict(evidence, node=nid, decision="scale_up",
                         dwell_s=self._dwell()))
        return {"action": "scale_up", "node": nid,
                "searchers": n_after, "evidence": evidence}

    def _abandon(self, nid: str, evidence: dict, reason: str) -> dict:
        """The admit publish failed (lost quorum / leadership): the
        provisioned node never became cluster state — stop it so
        nothing half-added keeps running."""
        node = self.resolve(nid) if self.resolve is not None else None
        if node is not None:
            node.stop()
        if self.on_retired is not None:
            self.on_retired(nid)
        self.abandoned += 1
        self._audit("autoscale.searchers", "provisioned", "abandoned",
                    dict(evidence, node=nid, decision="abandon_scale_up",
                         error=reason))
        return {"action": "abandoned", "node": nid, "reason": reason,
                "evidence": evidence}

    def _pick_victim(self, searchers: list) -> str:
        ours = [n for n in searchers if n.startswith(NODE_ID_PREFIX)]
        return max(ours or searchers)  # LIFO: newest autoscaled first

    def _scale_down(self, state, searchers: list, evidence: dict) -> dict:
        victim = self._pick_victim(searchers)
        node = self.resolve(victim) if self.resolve is not None else None
        res = retire_searcher(
            self.coordinator, victim, collector=self.collector,
            node=node, drain_timeout_s=self._drain_timeout(),
            audit=self._audit,
            rank=getattr(self.coordinator, "rank_fn", None))
        self.scale_downs += 1
        if res["hard_kill"]:
            self.hard_kills += 1
        self._last_scale = self.clock()
        self._cold_since = None
        if self.on_retired is not None:
            self.on_retired(victim)
        n_after = len(searchers) - 1
        self._sync_concurrency(n_after, evidence)
        self._audit("autoscale.searchers", len(searchers), n_after,
                    dict(evidence, node=victim, decision="scale_down",
                         drained=res["drained"],
                         hard_kill=res["hard_kill"],
                         drain_s=res["drain_s"]))
        return {"action": "scale_down", "node": victim,
                "searchers": n_after, "drain": res, "evidence": evidence}

    def _resume_drain(self, state, victim: str) -> dict:
        node = self.resolve(victim) if self.resolve is not None else None
        res = retire_searcher(
            self.coordinator, victim, collector=self.collector,
            node=node, drain_timeout_s=self._drain_timeout(),
            audit=self._audit,
            rank=getattr(self.coordinator, "rank_fn", None))
        self.scale_downs += 1
        if res["hard_kill"]:
            self.hard_kills += 1
        self._last_scale = self.clock()
        if self.on_retired is not None:
            self.on_retired(victim)
        self._audit("autoscale.searchers", "draining", "retired",
                    dict(decision="resume_drain", **res))
        return {"action": "resume_drain", "node": victim, "drain": res}

    def _sync_concurrency(self, n_searchers: int, evidence: dict) -> None:
        per = self.concurrency_per_searcher
        if not per:
            return
        old = self.admission.max_concurrent
        new = max(1, int(per) * max(1, int(n_searchers)))
        if new != old:
            self.admission.max_concurrent = new
            self._audit("autoscale.max_concurrent", old, new,
                        dict(evidence, searchers=n_searchers))

    # -- stats -------------------------------------------------------------

    def stats(self) -> dict:
        try:
            state = self.coordinator.state()
            searchers = self._searchers(state)
            draining = self._draining(state)
        except Exception:
            searchers, draining = [], []
        return {"enabled": self._on(),
                "leader": bool(self.coordinator.is_leader()),
                "min_searchers": self._min(),
                "max_searchers": self._max(),
                "dwell_s": self._dwell(),
                "cooldown_s": self._cooldown(),
                "drain_timeout_s": self._drain_timeout(),
                "searchers": searchers,
                "draining": draining,
                "ticks": self.ticks,
                "scale_ups": self.scale_ups,
                "scale_downs": self.scale_downs,
                "hard_kills": self.hard_kills,
                "abandoned": self.abandoned,
                "last_decision": dict(self.last_decision)}
