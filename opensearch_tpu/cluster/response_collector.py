"""Coordinator-side response statistics for adaptive replica selection.

Analog of ``node/ResponseCollectorService.java`` + the C3 rank math in
``ComputedNodeStats`` (ref OperationRouting.rankShardsAndUpdateStats):
the coordinator keeps, per data node, exponentially weighted moving
averages of

- the **response time** it measured around each shard query-phase RPC,
- the **service time** the node itself reported for executing the phase
  (piggybacked on the response, so queueing and transport delay are
  separable from execution cost), and
- the node's **search queue depth** (piggybacked too),

plus the node's self-reported **duress** flag (PR-4's
SearchBackpressureService verdict) with a freshness horizon.  Shard
copies are ranked with the C3 formula (Suresh et al., NSDI'15 — the
reference's adaptive replica selection): lower rank = better copy.
Nodes in duress are deranked but retained (they still serve as the copy
of last resort); nodes the coordinator has no response sample for rank
at the mean, so a stable sort preserves the legacy
primary-then-replicas order until real evidence arrives.

Every timing decision flows through the injectable ``clock`` so tests
drive EWMA decay and duress expiry deterministically —
``tools/check_monotonic.py`` enforces that this module never reads a
clock directly (tier-1).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

#: smoothing factor for every EWMA (the reference's
#: ExponentiallyWeightedMovingAverage alpha in ResponseCollectorService)
ALPHA = 0.3

#: exponent on the estimated queue length in the C3 rank — cubing makes
#: queue growth dominate once a node falls behind (queueAdjustmentFactor)
QUEUE_ADJUSTMENT_FACTOR = 3.0

#: a duress flag older than this many (injectable-clock) seconds is
#: stale: the node gets probed again instead of being shed forever
DURESS_TTL_S = 5.0

#: dynamic cluster settings (search.replica_selection.*) land on these
#: module globals like executor.DEFAULT_ALLOW_PARTIAL_RESULTS does —
#: consumers read them per search, so a settings flip is immediate
ADAPTIVE_ENABLED = True
SHED_ON_DURESS = True

#: single-search replica spill: a plain ``_search`` scatter rotates off
#: the preferred copy once the coordinator already has more than this
#: many outstanding query-phase RPCs against it (0 disables — msearch
#: batch rotation is unaffected either way)
SPILL_OUTSTANDING = 8

#: checkpoint-lag bound for the search-replica tier (dynamic
#: ``search.replication.max_lag``): a searcher whose piggybacked
#: replication lag (ops behind the last published checkpoint it has
#: seen) exceeds this is deranked like a duress node — retained as a
#: copy of last resort, never the preferred copy
SEARCH_MAX_LAG = 8

#: duress sheds consult the coordinator's own admission-gate occupancy:
#: a shard whose every copy reports duress is shed only when occupancy
#: >= this fraction — below it the coordinator has capacity to try the
#: duressed copy as a last resort.  0.0 = always shed (legacy PR-6
#: behavior); 1.0 = only shed at the 429 edge
SHED_OCCUPANCY = 0.0


class Ewma:
    """Exponentially weighted moving average; ``value`` is ``None``
    until the first sample (distinguishes "no evidence" from "fast")."""

    __slots__ = ("alpha", "value")

    def __init__(self, alpha: float = ALPHA,
                 initial: Optional[float] = None):
        self.alpha = float(alpha)
        self.value = initial

    def add(self, sample: float) -> float:
        if self.value is None:
            self.value = float(sample)
        else:
            self.value = (self.alpha * float(sample)
                          + (1.0 - self.alpha) * self.value)
        return self.value


class NodeStatistics:
    """One tracked node's EWMAs + duress flag (ComputedNodeStats)."""

    __slots__ = ("node_id", "queue_size", "response_time_nanos",
                 "service_time_nanos", "duress", "duress_updated",
                 "last_update", "failure_count", "response_count",
                 "outstanding", "search_lag")

    def __init__(self, node_id: str, now: float):
        self.node_id = node_id
        self.queue_size = Ewma()
        self.response_time_nanos = Ewma()
        self.service_time_nanos = Ewma()
        self.duress = False
        self.duress_updated = now
        self.last_update = now
        self.failure_count = 0
        self.response_count = 0
        self.outstanding = 0
        # search-replica checkpoint lag (ops behind the last published
        # checkpoint), piggybacked by searcher nodes; None = not a
        # searcher / no evidence yet
        self.search_lag = None


class ResponseCollectorService:
    """Per-node statistics registry feeding ``rank_copies`` (adaptive
    replica selection) and ``_nodes/stats`` ``adaptive_selection``."""

    def __init__(self,
                 clock: Callable[[], float] = time.monotonic,  # clock-default
                 duress_ttl_s: float = DURESS_TTL_S):
        self._clock = clock
        self.duress_ttl_s = float(duress_ttl_s)
        self._nodes: dict[str, NodeStatistics] = {}
        # eviction tombstones: node id -> eviction time.  A node the
        # cluster state just removed must not be resurrected by a LATE
        # in-flight response/ping — the resurrected entry would carry
        # the dead node's stale duress flag under a REFRESHED TTL (and
        # stale EWMAs) until the next state application purged it
        # again.  Tombstones expire after duress_ttl_s, or immediately
        # when the node rejoins (``readmit``).
        self._evicted: dict[str, float] = {}
        self._lock = threading.Lock()

    # -- ingestion ---------------------------------------------------------

    def _entry(self, node: str) -> NodeStatistics:
        st = self._nodes.get(node)
        if st is None:
            st = self._nodes[node] = NodeStatistics(node, self._clock())
        return st

    def _ingest_entry(self, node: str) -> Optional[NodeStatistics]:
        """The entry for an ingestion path (response/failure/ping), or
        None while the node sits under a live eviction tombstone —
        samples from a removed node are dropped, never resurrected.
        Caller holds the lock."""
        ts = self._evicted.get(node)
        if ts is not None:
            if self._clock() - ts <= self.duress_ttl_s:
                return None
            del self._evicted[node]      # tombstone expired: new node
        return self._entry(node)

    def _absorb_load(self, st: NodeStatistics, load: Optional[dict]):
        """Fold a piggybacked load snapshot (search response or fault-
        detection ping) into the node's stats.  Caller holds the lock."""
        if not load:
            return
        now = self._clock()
        if "queue_size" in load:
            st.queue_size.add(float(load["queue_size"]))
        svc = load.get("service_time_ewma_nanos")
        if svc:
            st.service_time_nanos.add(float(svc))
        if "duress" in load:
            st.duress = bool(load["duress"])
            st.duress_updated = now
        if "search_lag" in load:
            st.search_lag = int(load["search_lag"])
        st.last_update = now

    def record_response(self, node: str, response_time_nanos: float,
                        load: Optional[dict] = None) -> None:
        """One successful query-phase RPC: coordinator-measured response
        time plus whatever the node piggybacked."""
        with self._lock:
            st = self._ingest_entry(node)
            if st is None:
                return
            st.response_time_nanos.add(float(response_time_nanos))
            st.response_count += 1
            st.last_update = self._clock()
            self._absorb_load(st, load)

    def record_failure(self, node: str, elapsed_nanos: float) -> None:
        """A failed/timed-out RPC penalizes the node's response EWMA:
        the sample is the time the coordinator *wasted* (doubled, so a
        string of timeouts actually deranks the copy instead of
        averaging against stale fast samples)."""
        with self._lock:
            st = self._ingest_entry(node)
            if st is None:
                return
            prev = st.response_time_nanos.value or 0.0
            st.response_time_nanos.add(max(2.0 * float(elapsed_nanos),
                                           2.0 * prev))
            st.failure_count += 1
            st.last_update = self._clock()

    def record_ping_load(self, node: str, load: Optional[dict]) -> None:
        """Freshness fallback: fault-detection pings carry the same load
        snapshot, so duress/queue stay current on idle coordinators."""
        with self._lock:
            st = self._ingest_entry(node)
            if st is not None:
                self._absorb_load(st, load)

    def record_duress(self, node: str, in_duress: bool) -> None:
        """Direct seam (tests, local observations)."""
        with self._lock:
            st = self._ingest_entry(node)
            if st is None:
                return
            st.duress = bool(in_duress)
            st.duress_updated = self._clock()
            st.last_update = st.duress_updated

    def incr_outstanding(self, node: str) -> None:
        with self._lock:
            st = self._ingest_entry(node)
            if st is not None:
                st.outstanding += 1

    def decr_outstanding(self, node: str) -> None:
        with self._lock:
            st = self._nodes.get(node)
            if st is not None and st.outstanding > 0:
                st.outstanding -= 1

    def outstanding(self, node: str) -> int:
        """Coordinator-side in-flight query-phase RPCs against ``node``
        (the C3 q̂ ingredient; also the single-search spill signal)."""
        with self._lock:
            st = self._nodes.get(node)
            return 0 if st is None else st.outstanding

    def remove_node(self, node: str) -> None:
        """A node that left the cluster takes its stats with it — and
        leaves a tombstone so a late in-flight sample cannot resurrect
        the entry (stale duress flag and EWMAs) behind the state
        apply's back."""
        with self._lock:
            self._nodes.pop(node, None)
            self._evicted[node] = self._clock()

    def readmit(self, node: str) -> None:
        """Clear the eviction tombstone for a node present in the
        applied cluster state (rejoin, or never-evicted): its samples
        ingest normally again, starting from a clean slate."""
        with self._lock:
            self._evicted.pop(node, None)

    def tracked(self) -> set:
        with self._lock:
            return set(self._nodes)

    # -- ranking -----------------------------------------------------------

    def in_duress(self, node: str) -> bool:
        with self._lock:
            return self._in_duress_locked(node)

    def _in_duress_locked(self, node: str) -> bool:
        st = self._nodes.get(node)
        if st is None or not st.duress:
            return False
        # stale flags expire: a shed copy must get re-probed eventually
        return (self._clock() - st.duress_updated) <= self.duress_ttl_s

    def lagging(self, node: str) -> bool:
        with self._lock:
            return self._lagging_locked(node)

    def _lagging_locked(self, node: str) -> bool:
        """Search-replica checkpoint lag over the configured bound —
        the C3 derank trigger for stale searchers (lag has no TTL: the
        flag is refreshed by every ping/response the node answers, and
        a node that stops answering fails over on its own)."""
        st = self._nodes.get(node)
        return (st is not None and st.search_lag is not None
                and st.search_lag > SEARCH_MAX_LAG)

    def search_lag(self, node: str):
        with self._lock:
            st = self._nodes.get(node)
            return None if st is None else st.search_lag

    def _rank_locked(self, node: str, clients: int) -> Optional[float]:
        """C3 rank (lower = better); ``None`` until the coordinator has
        at least one measured response for the node."""
        st = self._nodes.get(node)
        if st is None or st.response_time_nanos.value is None:
            return None
        r_ms = st.response_time_nanos.value / 1e6
        mu = st.service_time_nanos.value
        mu_ms = max((mu if mu else st.response_time_nanos.value) / 1e6,
                    1e-3)
        q_bar = st.queue_size.value or 0.0
        q_hat = 1.0 + st.outstanding * max(clients, 1) + q_bar
        return (r_ms - 1.0 / mu_ms
                + (q_hat ** QUEUE_ADJUSTMENT_FACTOR) / mu_ms)

    def rank(self, node: str) -> Optional[float]:
        with self._lock:
            return self._rank_locked(node, len(self._nodes))

    def rank_copies(self, candidates: list) -> tuple:
        """Order shard copies best-first: healthy before duress
        (derank-but-retain), then by C3 rank.  Unranked nodes sit at the
        mean of the known ranks, and the sort is stable, so with no
        evidence the caller's legacy order survives untouched.  Returns
        ``(ordered, rerouted)`` — ``rerouted`` is True when adaptive
        selection changed the preferred copy."""
        with self._lock:
            clients = len(self._nodes)
            ranks = {n: self._rank_locked(n, clients) for n in candidates}
            # a lagging search replica is penalized exactly like a node
            # in duress: deranked behind every healthy copy, retained
            # as a copy of last resort (stale results beat no results
            # when nothing else answers under allow_partial)
            duress = {n: (self._in_duress_locked(n)
                          or self._lagging_locked(n))
                      for n in candidates}
            # unranked candidates sit at the FLEET mean (every tracked
            # node, not just this shard's copies): an unprobed replica
            # must beat a copy the coordinator has watched fall behind,
            # and must not displace copies performing at par (the
            # reference's adjusted-stats exploration)
            all_known = [r for r in (self._rank_locked(n, clients)
                                     for n in self._nodes)
                         if r is not None]
        known = [v for v in ranks.values() if v is not None]
        if not known and not any(duress.values()):
            return list(candidates), False
        mean = sum(all_known) / len(all_known) if all_known else 0.0
        ordered = sorted(candidates, key=lambda n: (
            duress[n], mean if ranks[n] is None else ranks[n]))
        return ordered, bool(ordered and candidates
                             and ordered[0] != candidates[0])

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        """The ``_nodes/stats`` ``adaptive_selection`` block: per tracked
        node, the EWMAs (ms), current rank, duress verdict, and sample
        freshness (the reference's AdaptiveSelectionStats)."""
        with self._lock:
            now = self._clock()
            clients = len(self._nodes)
            out = {}
            for node, st in sorted(self._nodes.items()):
                rank = self._rank_locked(node, clients)
                out[node] = {
                    "rank": None if rank is None else round(rank, 3),
                    "in_duress": self._in_duress_locked(node),
                    "search_lag": st.search_lag,
                    "search_lagging": self._lagging_locked(node),
                    "outstanding_requests": st.outstanding,
                    "avg_queue_size":
                        None if st.queue_size.value is None
                        else round(st.queue_size.value, 2),
                    "avg_response_time_ms":
                        None if st.response_time_nanos.value is None
                        else round(st.response_time_nanos.value / 1e6, 3),
                    "avg_service_time_ms":
                        None if st.service_time_nanos.value is None
                        else round(st.service_time_nanos.value / 1e6, 3),
                    "response_count": st.response_count,
                    "failure_count": st.failure_count,
                    "since_last_update_s":
                        round(max(0.0, now - st.last_update), 3),
                }
            return out
