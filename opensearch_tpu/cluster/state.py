"""Cluster state: the immutable, versioned snapshot every node applies.

Analog of ``cluster/ClusterState.java``: term + version ordering,
discovery nodes, index metadata, and a routing table assigning each
(index, shard) a primary node.  States travel as generic-value payloads
over the transport (full states; structural diffs are an optimization the
reference adds via cluster/Diff.java — semantics are identical).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Optional


@dataclass(frozen=True)
class ClusterState:
    cluster_name: str = "opensearch-tpu"
    term: int = 0
    version: int = 0
    master_node: Optional[str] = None
    # node_id -> {"name": ..., "address": ...}
    nodes: dict = field(default_factory=dict)
    # index -> {"settings": ..., "mappings": ...}
    indices: dict = field(default_factory=dict)
    # index -> [node_id per shard]
    routing: dict = field(default_factory=dict)

    def is_newer_than(self, other: "ClusterState") -> bool:
        return (self.term, self.version) > (other.term, other.version)

    def with_(self, **kw) -> "ClusterState":
        return replace(self, **kw)

    def to_payload(self) -> dict:
        return {
            "cluster_name": self.cluster_name,
            "term": self.term,
            "version": self.version,
            "master_node": self.master_node,
            "nodes": self.nodes,
            "indices": self.indices,
            "routing": self.routing,
        }

    @staticmethod
    def from_payload(p: dict) -> "ClusterState":
        return ClusterState(
            cluster_name=p.get("cluster_name", "opensearch-tpu"),
            term=int(p.get("term", 0)),
            version=int(p.get("version", 0)),
            master_node=p.get("master_node"),
            nodes=dict(p.get("nodes") or {}),
            indices=dict(p.get("indices") or {}),
            routing={k: list(v) for k, v in (p.get("routing") or {}).items()},
        )


def allocate_shards(state: ClusterState) -> ClusterState:
    """Round-robin primary allocation over data nodes — the
    BalancedShardsAllocator's job at the fidelity this needs: every shard
    gets exactly one assigned node, spread evenly, stable for already-
    assigned shards whose node is still in the cluster."""
    node_ids = sorted(state.nodes)
    if not node_ids:
        return state
    counts = {n: 0 for n in node_ids}
    routing = {}
    for index, meta in state.indices.items():
        n_shards = int((meta.get("settings") or {}).get("number_of_shards", 1))
        old = state.routing.get(index, [])
        assigned = []
        for s in range(n_shards):
            prev = old[s] if s < len(old) else None
            if prev in counts:
                assigned.append(prev)
                counts[prev] += 1
            else:
                assigned.append(None)
        routing[index] = assigned
    for index, assigned in routing.items():
        for s, node in enumerate(assigned):
            if node is None:
                target = min(sorted(counts), key=lambda n: counts[n])
                assigned[s] = target
                counts[target] += 1
    return state.with_(routing=routing)
