"""Cluster state: the immutable, versioned snapshot every node applies.

Analog of ``cluster/ClusterState.java``: term + version ordering,
discovery nodes, index metadata, and a routing table assigning each
(index, shard) a shard GROUP — primary + replicas + in-sync set +
primary term (the RoutingTable/ShardRouting + ReplicationTracker
in-sync-allocation-ids analog, ref index/seqno/ReplicationTracker.java:100
and cluster/routing/).  States travel as generic-value payloads over the
transport (full states; structural diffs are an optimization the
reference adds via cluster/Diff.java — semantics are identical).

Shard-group entry shape::

    {"primary": node_id | None,
     "replicas": [node_id, ...],
     "in_sync": [node_id, ...],     # copies safe to promote / must ack
     "primary_term": int}           # bumped on every promotion (fencing)
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Optional


def copies_of(entry: dict) -> list:
    """All nodes holding a WRITE copy of the shard, primary first.
    Search-only replicas are deliberately excluded: they never ack
    writes and never join the in-sync set (``search_copies_of``)."""
    out = [entry["primary"]] if entry.get("primary") else []
    out.extend(entry.get("replicas") or [])
    return out


def search_copies_of(entry: dict) -> list:
    """Search-only replica copies that completed their remote-store
    refill (reported ready) — the searcher tier's serving set."""
    ready = entry.get("search_in_sync") or []
    return [n for n in (entry.get("search_replicas") or []) if n in ready]


def node_roles(info: Optional[dict]) -> set:
    """A node's role set from its discovery info.  Nodes that predate
    roles (or joined with bare info) keep the legacy behavior: full
    master-eligible data nodes."""
    roles = (info or {}).get("roles")
    if roles is None:
        return {"master", "data"}
    return set(roles)


@dataclass(frozen=True)
class ClusterState:
    cluster_name: str = "opensearch-tpu"
    term: int = 0
    version: int = 0
    master_node: Optional[str] = None
    # node_id -> {"name": ..., "address": ...}
    nodes: dict = field(default_factory=dict)
    # index -> {"settings": ..., "mappings": ...}
    indices: dict = field(default_factory=dict)
    # index -> [shard-group entry per shard] (see module docstring)
    routing: dict = field(default_factory=dict)
    # master-eligible node ids whose majority elects and commits
    # (CoordinationMetadata.VotingConfiguration; [] = not yet set, the
    # coordinator falls back to its bootstrap configuration)
    voting: tuple = ()

    def is_newer_than(self, other: "ClusterState") -> bool:
        return (self.term, self.version) > (other.term, other.version)

    def with_(self, **kw) -> "ClusterState":
        return replace(self, **kw)

    def to_payload(self) -> dict:
        return {
            "cluster_name": self.cluster_name,
            "term": self.term,
            "version": self.version,
            "master_node": self.master_node,
            "nodes": self.nodes,
            "indices": self.indices,
            "routing": self.routing,
            "voting": list(self.voting),
        }

    @staticmethod
    def from_payload(p: dict) -> "ClusterState":
        return ClusterState(
            cluster_name=p.get("cluster_name", "opensearch-tpu"),
            term=int(p.get("term", 0)),
            version=int(p.get("version", 0)),
            master_node=p.get("master_node"),
            nodes=dict(p.get("nodes") or {}),
            indices=dict(p.get("indices") or {}),
            routing={k: [dict(e) for e in v]
                     for k, v in (p.get("routing") or {}).items()},
            voting=tuple(p.get("voting") or ()),
        )


# -- state diffs (cluster/Diff.java / DiffableUtils analog) -----------------

_DIFF_DICTS = ("nodes", "indices", "routing")
_DIFF_SCALARS = ("cluster_name", "term", "version", "master_node", "voting")


def diff_states(old: "ClusterState", new: "ClusterState") -> dict:
    """Entry-level diff of two payloads keyed by the base (term, version)
    — the receiver may only apply it over exactly that accepted state
    (PublishRequest's Diff path; full-state fallback on mismatch)."""
    oldp, newp = old.to_payload(), new.to_payload()
    d = {"base_term": old.term, "base_version": old.version}
    for k in _DIFF_SCALARS:
        d[k] = newp[k]
    for k in _DIFF_DICTS:
        set_, del_ = {}, []
        for key, v in newp[k].items():
            if oldp[k].get(key) != v:
                set_[key] = v
        for key in oldp[k]:
            if key not in newp[k]:
                del_.append(key)
        d[k] = {"set": set_, "del": del_}
    return d


def apply_diff(base: "ClusterState", diff: dict) -> "ClusterState":
    """Reconstruct the full state a diff describes over ``base`` (the
    caller must have checked base identity)."""
    p = base.to_payload()
    for k in _DIFF_SCALARS:
        p[k] = diff[k]
    for k in _DIFF_DICTS:
        merged = dict(p[k])
        for key in diff[k]["del"]:
            merged.pop(key, None)
        merged.update(diff[k]["set"])
        p[k] = merged
    return ClusterState.from_payload(p)


def _alloc_setting(settings: dict, suffix: str):
    """Read index.routing.allocation.<suffix> in flat or nested form."""
    for key in (f"index.routing.allocation.{suffix}",
                f"routing.allocation.{suffix}"):
        if key in settings:
            return settings[key]
    node = settings.get("routing") or {}
    node = node.get("allocation") or {}
    for part in suffix.split("."):
        if not isinstance(node, dict):
            return None
        node = node.get(part)
        if node is None:
            return None
    return node


def _as_name_set(v):
    if v is None:
        return None
    if isinstance(v, str):
        return {s.strip() for s in v.split(",") if s.strip()}
    return set(v)


def node_allowed(index_settings: dict, node_id: str) -> bool:
    """The decider chain's filter deciders (cluster/routing/allocation/
    decider/FilterAllocationDecider.java): include/exclude/require by
    node name.  Same-shard and shards-per-node deciders apply at the
    candidate-selection site."""
    exclude = _as_name_set(_alloc_setting(index_settings, "exclude._name"))
    if exclude and node_id in exclude:
        return False
    include = _as_name_set(_alloc_setting(index_settings, "include._name"))
    if include is not None and include and node_id not in include:
        return False
    require = _as_name_set(_alloc_setting(index_settings, "require._name"))
    if require and node_id not in require:
        return False
    return True


def _shards_per_node_cap(index_settings: dict):
    v = _alloc_setting(index_settings, "total_shards_per_node")
    return None if v is None else int(v)


def allocate_shards(state: ClusterState, *,
                    rank=None) -> ClusterState:
    """Shard-group allocation over data nodes — the BalancedShardsAllocator
    + in-sync-promotion logic at the fidelity this needs:

    - stable: copies on still-alive nodes stay put;
    - a lost primary is replaced by an IN-SYNC replica (safe promotion)
      or, failing that, a stale replica (best effort — last resort, like
      the reference's allocate_stale_primary reroute command), bumping the
      primary term either way so stale primaries are fenced;
    - replica slots are (re)filled on the least-loaded nodes that don't
      already hold a copy of the shard; new replicas start OUTSIDE the
      in-sync set and join it when peer recovery completes
      (ReplicationTracker.markAllocationIdAsInSync analog);
    - a fresh primary with no surviving copy starts empty with an
      in-sync set of just itself;
    - ``number_of_search_replicas`` slots are filled on search-role
      nodes only (the ingest/search tier separation): search replicas
      never hold write copies, start OUTSIDE ``search_in_sync`` and
      join it when their remote-store refill completes.  Write copies
      (primary/replicas) are conversely never placed on search-only
      nodes;
    - a node marked ``draining`` (the autoscaler's retirement marker)
      is excluded from the searcher pool, so committing the marker
      vacates its ``search_replicas``/``search_in_sync`` slots in the
      same state update;
    - ``rank`` (optional ``node_id -> float|None``, the C3 collector's
      adaptive rank) breaks least-loaded ties when filling write-copy
      holes: among equally-loaded candidates the healthiest
      (lowest-ranked) node wins.  With no samples every rank is None
      and the routing table is byte-identical to the legacy order.
    """
    node_ids = sorted(n for n, info in state.nodes.items()
                      if "data" in node_roles(info))
    search_nodes = sorted(n for n, info in state.nodes.items()
                          if "search" in node_roles(info)
                          and not (info or {}).get("draining"))
    if not node_ids:
        return state

    def health(n):
        if rank is None:
            return 0.0
        r = rank(n)
        return float("inf") if r is None else float(r)

    counts = {n: 0 for n in node_ids}
    s_counts = {n: 0 for n in search_nodes}
    routing: dict = {}
    # pass 1: retain what survives, decide promotions
    for index, meta in state.indices.items():
        settings = meta.get("settings") or {}
        n_shards = int(settings.get("number_of_shards", 1))
        want_repl = min(int(settings.get("number_of_replicas", 0)),
                        len(node_ids) - 1)
        want_search = min(
            int(settings.get("number_of_search_replicas", 0) or 0),
            len(search_nodes))
        old = state.routing.get(index, [])
        entries = []
        for s in range(n_shards):
            o = old[s] if s < len(old) and isinstance(old[s], dict) else None
            primary = o["primary"] if o else None
            replicas = [r for r in (o.get("replicas") or []) if r in counts] \
                if o else []
            in_sync = [n for n in (o.get("in_sync") or []) if n in counts] \
                if o else []
            term = int(o.get("primary_term", 1)) if o else 1
            if primary not in counts:
                lost_primary = primary is not None
                promo = next((r for r in replicas if r in in_sync), None)
                if promo is None and replicas:
                    promo = replicas[0]        # stale promotion, last resort
                    in_sync = []               # its history is authoritative now
                primary = promo                # may still be None
                if promo is not None:
                    replicas.remove(promo)
                if lost_primary:
                    # bump on EVERY primary change — including the
                    # no-surviving-copy path (a fresh empty primary gets
                    # assigned in pass 2): a rejoining old primary must
                    # not share a term with the new lineage, or replica
                    # term fencing cannot tell the two apart
                    term += 1
            entry = {"primary": primary, "replicas": replicas,
                     "in_sync": in_sync, "primary_term": term,
                     "_want": want_repl, "_want_search": want_search}
            # keep legacy entries byte-identical: the search-tier keys
            # only appear once an index asks for (or held) searchers
            s_repl = [r for r in (o.get("search_replicas") or [])
                      if r in s_counts] if o else []
            if want_search or s_repl:
                entry["search_replicas"] = s_repl
                entry["search_in_sync"] = [
                    n for n in (o.get("search_in_sync") or [])
                    if n in s_repl] if o else []
            entries.append(entry)
        routing[index] = entries
    for entries in routing.values():
        for e in entries:
            if e["primary"] is not None:
                counts[e["primary"]] += 1
            for r in e["replicas"]:
                counts[r] += 1
            for r in e.get("search_replicas") or []:
                s_counts[r] += 1
    # pass 2: fill holes on least-loaded distinct nodes that the decider
    # chain allows (filter deciders + same-shard + shards-per-node —
    # cluster/routing/allocation/decider/)
    def index_shard_count(index, node):
        return sum((1 if e2["primary"] == node else 0)
                   + e2["replicas"].count(node)
                   for e2 in routing[index])

    for index, entries in routing.items():
        isettings = (state.indices.get(index) or {}).get("settings") or {}
        cap = _shards_per_node_cap(isettings)

        def allowed(node, holders):
            if node in holders:
                return False               # SameShardAllocationDecider
            if not node_allowed(isettings, node):
                return False               # FilterAllocationDecider
            if cap is not None and index_shard_count(index, node) >= cap:
                return False               # ShardsLimitAllocationDecider
            return True

        for e in entries:
            if e["primary"] is None:
                cands = [n for n in sorted(counts) if allowed(n, set())]
                if not cands:
                    cands = sorted(counts)  # a primary MUST live somewhere
                target = min(cands,
                             key=lambda n: (counts[n], health(n), n))
                e["primary"] = target
                counts[target] += 1
                e["in_sync"] = []              # fresh shard: no history
            holders = set(copies_of(e))
            while len(e["replicas"]) < e["_want"]:
                cands = [n for n in sorted(counts)
                         if allowed(n, holders)]
                if not cands:
                    break
                target = min(cands,
                             key=lambda n: (counts[n], health(n), n))
                e["replicas"].append(target)
                holders.add(target)
                counts[target] += 1
            del e["_want"]
            # the primary is always in-sync; drop in-sync entries that no
            # longer hold a copy
            e["in_sync"] = ([e["primary"]]
                            + [n for n in e["in_sync"]
                               if n != e["primary"] and n in holders])
            # search-replica slots: trim past the (possibly shrunk)
            # want, then fill holes on the least-loaded search nodes —
            # a fresh slot starts outside search_in_sync until the
            # searcher reports its remote refill done
            want_search = e.pop("_want_search", 0)
            if "search_replicas" in e or want_search:
                s_repl = list(e.get("search_replicas") or [])
                for gone in s_repl[want_search:]:
                    s_counts[gone] -= 1
                s_repl = s_repl[:want_search]
                while len(s_repl) < want_search:
                    # a dual-role node already holding a write copy of
                    # this shard is skipped (SameShardAllocationDecider
                    # across tiers)
                    cands = [n for n in sorted(s_counts)
                             if n not in s_repl and n not in holders]
                    if not cands:
                        break
                    target = min(cands, key=lambda n: s_counts[n])
                    s_repl.append(target)
                    s_counts[target] += 1
                e["search_replicas"] = s_repl
                e["search_in_sync"] = [
                    n for n in (e.get("search_in_sync") or [])
                    if n in s_repl]
    return state.with_(routing=routing)
