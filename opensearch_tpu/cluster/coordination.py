"""Cluster coordination: pre-vote, term-based election, joins, two-phase
state publication, leader/follower failure detection.

Analog of ``cluster/coordination/Coordinator.java`` (startElection :499,
handleJoinRequest :575, becomeLeader :697, publish :1246) +
``PreVoteCollector`` / ``JoinHelper`` / ``Publication`` /
``LeaderChecker`` / ``FollowersChecker`` — the Zen2 protocol at its
correctness core:

- a candidate pre-votes (am I electable? is my state fresh enough?) then
  increments its term and solicits joins; a majority of the voting
  configuration makes it leader for that term;
- the leader publishes state as phase-1 PUBLISH (followers validate the
  term, persist as *accepted*, ack) and phase-2 COMMIT once a majority
  acked — committed states apply on every node;
- a node that sees a higher term steps down to candidate;
- followers check the leader (and the leader its followers) with periodic
  pings; repeated failures trigger elections / node removal.

The voting configuration is the initial master-eligible node set (static;
the reference's dynamic reconfiguration is orthogonal to the protocol
spine).  All timers are injectable so tests can drive the protocol
deterministically (the DisruptableMockTransport technique, SURVEY §4.3).
"""

from __future__ import annotations

import random
import threading
import zlib as _zlib
from enum import Enum
from typing import Callable, Optional

from opensearch_tpu.common.errors import (NodeDisconnectedError,
                                          OpenSearchTpuError)
from opensearch_tpu.cluster import fault_detection as fd
from opensearch_tpu.cluster.state import ClusterState, allocate_shards
from opensearch_tpu.transport.service import TransportService

PREVOTE = "internal:cluster/coordination/prevote"
JOIN = "internal:cluster/coordination/join"
PUBLISH = "internal:cluster/coordination/publish"
COMMIT = "internal:cluster/coordination/commit"
# legacy fault-detection action names (pre fault_detection.py); still
# registered so mixed-version peers keep getting answers
LEADER_CHECK = "internal:cluster/coordination/leader_check"
FOLLOWER_CHECK = "internal:cluster/coordination/follower_check"


class CoordinationError(OpenSearchTpuError):
    status = 500


class FailedToCommitError(CoordinationError):
    pass


class Mode(Enum):
    CANDIDATE = "CANDIDATE"
    LEADER = "LEADER"
    FOLLOWER = "FOLLOWER"


class Coordinator:
    def __init__(self, node_id: str, transport: TransportService,
                 voting_nodes: list[str], node_info: Optional[dict] = None,
                 on_apply: Optional[Callable[[ClusterState], None]] = None,
                 check_interval: float = 1.0, check_retries: int = 3,
                 check_timeout: float = 2.0, gateway=None,
                 load_provider=None, on_node_load=None,
                 health_provider=None):
        self.node_id = node_id
        self.transport = transport
        # bootstrap voting configuration; once states carry a `voting`
        # field (dynamic reconfiguration) the accepted/committed one wins
        self._initial_voting = sorted(voting_nodes)
        self.node_info = node_info or {"name": node_id}
        self.on_apply = on_apply
        self.check_interval = check_interval
        self.check_retries = check_retries
        self.gateway = gateway          # GatewayStateStore | None
        # node-health gate (FsHealthService wiring): an UNHEALTHY node
        # must neither stand for election nor keep the lead — the
        # reference's NodeHealthService veto in Coordinator/PreVote
        self.health_provider = health_provider
        # optional node_id -> float|None (the C3 collector's adaptive
        # rank): threaded into allocate_shards so write-copy hole
        # filling prefers healthier nodes when evidence exists
        self.rank_fn = None

        self.mode = Mode.CANDIDATE
        self.current_term = 0
        self.last_join_term = 0         # highest term we voted (joined) in
        self.accepted: ClusterState = ClusterState()
        self.committed: ClusterState = ClusterState()
        if gateway is not None:
            # restart: restore terms (votes MUST survive — a node that
            # voted in term T may never vote again in T), the accepted
            # state, and the committed state when the commit marker still
            # names the accepted (term, version)
            persisted = gateway.load()
            self.current_term = persisted["current_term"]
            self.last_join_term = persisted["last_join_term"]
            if persisted["accepted"] is not None:
                self.accepted = ClusterState.from_payload(
                    persisted["accepted"])
                if persisted["commit"] == (self.accepted.term,
                                           self.accepted.version):
                    self.committed = self.accepted
        self._lock = threading.RLock()
        # serializes compute+publish end-to-end (MasterService single
        # thread analog) — without it two concurrent updates both build
        # version+1 and the loser's failed quorum demotes a healthy leader
        self._update_lock = threading.Lock()
        self._check_failures: dict[str, int] = {}
        self._stopped = False
        self._timer: Optional[threading.Timer] = None
        # fault detection proper lives in cluster/fault_detection.py; the
        # failure counters are SHARED so _leader_alive sees what the
        # checkers see
        fd_settings = fd.FaultDetectionSettings(
            interval=check_interval, timeout=check_timeout,
            retries=check_retries)
        # both checkers piggyback the node's load snapshot on their ping
        # responses and surface the peer's to on_node_load — the
        # freshness fallback adaptive replica selection leans on when no
        # search traffic is reaching a node
        self.follower_checker = fd.FollowerChecker(
            transport, node_id, fd_settings, self._check_failures,
            self._on_follower_failure, load_provider=load_provider,
            on_node_load=on_node_load)
        self.leader_checker = fd.LeaderChecker(
            transport, node_id, fd_settings, self._check_failures,
            self._on_leader_failure, load_provider=load_provider,
            on_node_load=on_node_load)

        t = transport
        t.register_handler(PREVOTE, self._on_prevote)
        t.register_handler(JOIN, self._on_join)
        t.register_handler(PUBLISH, self._on_publish)
        t.register_handler(COMMIT, self._on_commit)
        for action in (LEADER_CHECK, fd.LEADER_CHECK):
            t.register_handler(action, self._on_leader_check)
        for action in (FOLLOWER_CHECK, fd.FOLLOWER_CHECK):
            t.register_handler(action, self._on_follower_check)

    # -- helpers ----------------------------------------------------------

    def _persist_terms(self):
        """Durably record the vote BEFORE acting on it (call with lock)."""
        if self.gateway is not None:
            self.gateway.save_terms(self.current_term, self.last_join_term)

    @property
    def voting_nodes(self) -> list[str]:
        """Current voting configuration: the committed state's (falling
        back to accepted, then the bootstrap set) —
        CoordinationMetadata.getLastCommittedConfiguration."""
        v = self.committed.voting or self.accepted.voting
        return sorted(v) if v else self._initial_voting

    def _majority(self) -> int:
        return len(self.voting_nodes) // 2 + 1

    def _reconfigure(self, nodes: dict) -> tuple:
        """Voting config for a node set: every master-eligible node,
        trimmed to an odd count so a single failure never halves the
        quorum (cluster/coordination/Reconfigurator.java)."""
        eligible = sorted(n for n, info in nodes.items()
                          if (info or {}).get("master_eligible", True))
        if not eligible:
            return tuple(self._initial_voting)
        if len(eligible) % 2 == 0 and len(eligible) > 1:
            for cand in reversed(eligible):
                if cand != self.node_id:
                    eligible.remove(cand)
                    break
        return tuple(eligible)

    def is_leader(self) -> bool:
        return self.mode == Mode.LEADER

    def state(self) -> ClusterState:
        with self._lock:
            return self.committed

    # -- election ---------------------------------------------------------

    def _node_unhealthy(self) -> bool:
        try:
            return (self.health_provider is not None
                    and not self.health_provider())
        except Exception:  # noqa: BLE001 — a broken probe must not wedge
            return False

    def start_election(self) -> bool:
        """Pre-vote, then solicit joins for term+1.  Returns True if this
        node became leader.  An unhealthy node (failed fsync probe)
        refuses to stand — electing a leader that can't persist votes or
        accepted states voids every durability argument."""
        if self._node_unhealthy():
            return False
        with self._lock:
            if self._stopped or self.mode == Mode.LEADER:
                return self.mode == Mode.LEADER
            my_term = self.current_term
            my_version = self.accepted.version
        grants = 1
        for peer in self.voting_nodes:
            if peer == self.node_id:
                continue
            try:
                r = self.transport.send_request(
                    peer, PREVOTE,
                    {"term": my_term, "version": my_version,
                     "source": self.node_id}, timeout=2.0)
                if r.get("granted"):
                    grants += 1
            except OpenSearchTpuError:
                continue
        if grants < self._majority():
            return False

        with self._lock:
            new_term = self.current_term + 1
            self.current_term = new_term
            self.last_join_term = new_term   # vote for ourselves
            self._persist_terms()
            state_term = self.accepted.term
            state_version = self.accepted.version
        joins = 1
        joiners: dict[str, dict] = {}
        for peer in self.voting_nodes:
            if peer == self.node_id:
                continue
            try:
                r = self.transport.send_request(
                    peer, JOIN, {"term": new_term, "source": self.node_id,
                                 "state_term": state_term,
                                 "state_version": state_version},
                    timeout=2.0)
                if r.get("joined"):
                    joins += 1
                    joiners[peer] = r.get("info") or {"name": peer}
            except OpenSearchTpuError:
                continue
        if joins < self._majority():
            return False
        return self._become_leader(new_term, joiners)

    def _become_leader(self, term: int, joiners: dict[str, dict]) -> bool:
        with self._lock:
            if self.current_term != term or self._stopped:
                return False
            self.mode = Mode.LEADER
            self._check_failures.clear()
            base = (self.accepted
                    if self.accepted.is_newer_than(self.committed)
                    else self.committed)
            nodes = dict(base.nodes)
            nodes[self.node_id] = self.node_info
            nodes.update(joiners)
            first = base.with_(term=term, version=base.version + 1,
                               master_node=self.node_id, nodes=nodes,
                               voting=self._reconfigure(nodes))
        try:
            self.publish(first)
        except FailedToCommitError:
            with self._lock:
                self.mode = Mode.CANDIDATE
            return False
        self._schedule_checks()
        return True

    def _on_prevote(self, payload: dict) -> dict:
        with self._lock:
            # freshness is judged against our ACCEPTED state: a committed
            # version exists on a majority as *accepted*, so gating on
            # accepted is what makes committed states survive elections
            ours = (self.accepted.term, self.accepted.version)
            theirs = (payload["term"], payload["version"])
            granted = theirs >= ours and (self.mode != Mode.FOLLOWER
                                          or not self._leader_alive())
            return {"granted": bool(granted)}

    def _leader_alive(self) -> bool:
        return (self.committed.master_node is not None
                and self._check_failures.get(
                    self.committed.master_node, 0) < self.check_retries)

    def _on_join(self, payload: dict) -> dict:
        with self._lock:
            term = payload["term"]
            if term <= self.last_join_term:
                return {"joined": False, "term": self.current_term}
            # same accepted-state gate as the prevote: never vote for a
            # candidate whose state is older than what we accepted — a
            # committed state lives on a majority as accepted, so a stale
            # candidate cannot reach quorum (leader completeness)
            theirs = (payload.get("state_term", 0),
                      payload.get("state_version", 0))
            if theirs < (self.accepted.term, self.accepted.version):
                return {"joined": False, "term": self.current_term}
            self.last_join_term = term
            if term > self.current_term:
                self.current_term = term
                if self.mode == Mode.LEADER:
                    self.mode = Mode.CANDIDATE
            self._persist_terms()
            return {"joined": True, "info": self.node_info}

    # -- node membership (leader side) ------------------------------------

    def add_node(self, node_id: str, info: dict):  # actuator-ok (membership primitive; callers audit)
        """Leader: admit a node; master-eligible joiners grow the voting
        configuration (dynamic reconfiguration)."""
        def update(state: ClusterState) -> ClusterState:
            nodes = dict(state.nodes)
            nodes[node_id] = info
            return allocate_shards(state.with_(
                nodes=nodes, voting=self._reconfigure(nodes)),
                rank=self.rank_fn)
        self.submit_state_update(update)

    def remove_node(self, node_id: str):  # actuator-ok (membership primitive; callers audit)
        def update(state: ClusterState) -> ClusterState:
            if node_id not in state.nodes:
                return state
            nodes = dict(state.nodes)
            del nodes[node_id]
            return allocate_shards(state.with_(
                nodes=nodes, voting=self._reconfigure(nodes)),
                rank=self.rank_fn)
        self.submit_state_update(update)

    # -- publication ------------------------------------------------------

    def submit_state_update(self, fn: Callable[[ClusterState], ClusterState]):
        """Leader-only, serialized (MasterService.runTasks analog)."""
        with self._update_lock:
            with self._lock:
                if self.mode != Mode.LEADER:
                    raise CoordinationError(
                        f"[{self.node_id}] is not the elected cluster manager")
                new_state = fn(self.committed)
                if new_state is self.committed:
                    return self.committed
                new_state = new_state.with_(
                    term=self.current_term,
                    version=self.committed.version + 1,
                    master_node=self.node_id)
            self.publish(new_state)
        return new_state

    def publish(self, state: ClusterState):
        """Two-phase: PUBLISH to every node in the state (as a DIFF over
        the previous committed state when possible, falling back to the
        full state on a base mismatch — PublishRequest's Diff path),
        COMMIT once a quorum acked.  During a voting reconfiguration the
        quorum must hold in BOTH the old (committed) and new
        configurations (the Zen2 joint-consensus rule)."""
        from opensearch_tpu.cluster.state import diff_states

        with self._lock:
            base = self.committed
            old_config = set(self.voting_nodes)
        new_config = set(state.voting) or old_config
        payload = state.to_payload()
        diff = (diff_states(base, state)
                if base.version > 0 and base.master_node == self.node_id
                else None)
        targets = [n for n in state.nodes if n != self.node_id]
        ok_nodes = []
        acked = set()
        local = self._on_publish({"state": payload})   # accept locally first
        if local.get("accepted"):
            acked.add(self.node_id)
        from opensearch_tpu.common.retry import retry_call

        def publish_to(peer):
            if diff is not None:
                r = self.transport.send_request(peer, PUBLISH,
                                                {"diff": diff},
                                                timeout=5.0)
                if not r.get("accepted") and r.get("need_full"):
                    # receiver holds a different base: full state
                    r = self.transport.send_request(
                        peer, PUBLISH, {"state": payload}, timeout=5.0)
                return r
            return self.transport.send_request(peer, PUBLISH,
                                               {"state": payload},
                                               timeout=5.0)

        for peer in targets:
            try:
                # one fast retry on a dropped frame: a transient blip
                # must not demote a healthy leader over a lost quorum.
                # Only disconnects retry — a RECEIVE timeout already
                # spent its 5s budget and blocking publication further
                # helps nobody
                r = retry_call("publication",
                               lambda peer=peer: publish_to(peer),
                               retry_on=(NodeDisconnectedError,),
                               max_attempts=2, base_delay=0.02,
                               seed=_zlib.crc32(peer.encode()))
                if r.get("accepted"):
                    ok_nodes.append(peer)
                    acked.add(peer)
            except OpenSearchTpuError:
                continue

        def quorum(config: set) -> bool:
            return len(acked & config) >= len(config) // 2 + 1

        if not (quorum(old_config) and quorum(new_config)):
            with self._lock:
                self.mode = Mode.CANDIDATE
            raise FailedToCommitError(
                f"publication of term {state.term} version {state.version} "
                f"got {sorted(acked)} acks, needs majorities of "
                f"{sorted(old_config)} and {sorted(new_config)}")
        self._on_commit({"term": state.term, "version": state.version})
        for peer in ok_nodes:
            try:
                self.transport.send_request(
                    peer, COMMIT,
                    {"term": state.term, "version": state.version},
                    timeout=5.0)
            except OpenSearchTpuError:
                continue

    def _on_publish(self, payload: dict) -> dict:
        if "diff" in payload:
            from opensearch_tpu.cluster.state import apply_diff

            diff = payload["diff"]
            with self._lock:
                if (self.accepted.term, self.accepted.version) != \
                        (diff["base_term"], diff["base_version"]):
                    # can't apply: ask for the full state
                    return {"accepted": False, "need_full": True,
                            "term": self.current_term}
                state = apply_diff(self.accepted, diff)
        else:
            state = ClusterState.from_payload(payload["state"])
        with self._lock:
            if state.term < self.current_term:
                return {"accepted": False, "term": self.current_term}
            if (state.term, state.version) <= (self.accepted.term,
                                               self.accepted.version):
                return {"accepted": False, "term": self.current_term}
            self.current_term = max(self.current_term, state.term)
            self.accepted = state
            if self.gateway is not None:
                # accepted state is durable BEFORE the ack: the quorum
                # intersection argument needs it present after a crash
                # (PersistedClusterStateService on PublishRequest) — the
                # FULL reconstructed state, even when a diff arrived
                self._persist_terms()
                self.gateway.save_accepted(state.to_payload())
            if state.master_node != self.node_id:
                self.mode = Mode.FOLLOWER
                self._check_failures.clear()
            return {"accepted": True}

    def _on_commit(self, payload: dict) -> dict:
        with self._lock:
            if (self.accepted.term == payload["term"]
                    and self.accepted.version == payload["version"]
                    and self.accepted.is_newer_than(self.committed)):
                self.committed = self.accepted
                if self.gateway is not None:
                    self.gateway.save_commit(self.committed.term,
                                             self.committed.version)
                apply_cb = self.on_apply
                state = self.committed
            else:
                return {"applied": False}
        if apply_cb is not None:
            apply_cb(state)
        return {"applied": True}

    # -- failure detection ------------------------------------------------

    def _on_leader_check(self, payload: dict) -> dict:
        # follower asks: are you still my leader?
        with self._lock:
            return self.leader_checker.handle_check(
                payload, is_leader=self.mode == Mode.LEADER,
                term=self.current_term)

    def _on_follower_check(self, payload: dict) -> dict:
        # leader asks follower: still following me in this term?  The
        # applied version rides along for the LagDetector.
        with self._lock:
            return self.follower_checker.handle_check(
                payload, term=self.current_term,
                is_follower=self.mode == Mode.FOLLOWER,
                applied_version=self.committed.version)

    def _on_follower_failure(self, peer: str, reason: str):  # actuator-ok (fault eviction, not a policy decision)
        """FollowerChecker verdict: publish a state removing the node
        (allocate_shards promotes its replicas on the way out)."""
        try:
            self.remove_node(peer)
        except CoordinationError:
            pass   # lost the lead mid-round; the new leader re-detects

    def _on_leader_failure(self, leader: str):
        """LeaderChecker verdict: the master is gone — become candidate
        and re-elect."""
        with self._lock:
            self.mode = Mode.CANDIDATE
        self.start_election()

    def run_checks_once(self):
        """One failure-detection round (scheduled repeatedly in production,
        callable directly in deterministic tests)."""
        with self._lock:
            mode = self.mode
            state = self.committed
            term = self.current_term
        if mode == Mode.LEADER and self._node_unhealthy():
            # abdicate: a leader whose disk stopped taking writes cannot
            # safely persist accepted states; stepping down lets a
            # healthy node win the next election (elections gate on
            # health, so THIS node won't immediately re-stand)
            with self._lock:
                self.mode = Mode.CANDIDATE
            return
        if mode == Mode.LEADER:
            self.follower_checker.check_round(state, term)
        elif mode == Mode.FOLLOWER and state.master_node:
            self.leader_checker.check_round(state.master_node)
        elif mode == Mode.CANDIDATE:
            self.start_election()

    def _schedule_checks(self):
        if self._stopped:
            return
        with self._lock:
            if self._timer is not None:
                return
        self._tick()

    def _tick(self):
        if self._stopped:
            return
        try:
            self.run_checks_once()
        except Exception:
            pass
        jitter = self.check_interval * (1.0 + random.random() * 0.2)
        self._timer = threading.Timer(jitter, self._tick)
        self._timer.daemon = True
        self._timer.start()

    def start(self):
        """Begin periodic failure detection + candidate elections."""
        self._schedule_checks()

    def stop(self):
        self._stopped = True
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
