"""ClusterNode: a full cluster member — coordinator + transport +
shard-subset indices + distributed document/search actions.

Analog of the action layer (L6) on the cluster runtime (L4):

- index admin ops proxy to the elected cluster-manager, which mutates the
  cluster state and publishes (TransportCreateIndexAction ->
  MetadataCreateIndexService -> MasterService, call stack SURVEY §3.4);
- applied states create/remove LOCAL shards per the routing table
  (indices/cluster/IndicesClusterStateService.java);
- document ops route by murmur3 to the owning node
  (TransportBulkAction :213 grouping / OperationRouting);
- search scatter-gathers: shards grouped per node, one RPC each, host
  merge of top-k (AbstractSearchAsyncAction :223 + SearchPhaseController
  merge).  Per-shard scoring stats, like the reference's default
  query_then_fetch.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from opensearch_tpu.search.executor import merge_hit_rows

from opensearch_tpu.common.errors import (
    IndexNotFoundError,
    OpenSearchTpuError,
    ShardNotFoundError,
    ValidationError,
)
from opensearch_tpu.cluster.coordination import CoordinationError, Coordinator
from opensearch_tpu.cluster.state import ClusterState, allocate_shards
from opensearch_tpu.indices.service import IndexService
from opensearch_tpu.transport.service import TransportService

A_CREATE_INDEX = "cluster:admin/index/create"
A_DELETE_INDEX = "cluster:admin/index/delete"
A_WRITE_SHARD = "indices:data/write/shard"
A_GET_DOC = "indices:data/read/get"
A_SEARCH_SHARDS = "indices:data/read/search[shards]"
A_REFRESH = "indices:admin/refresh"


class NoMasterError(CoordinationError):
    status = 503


class ClusterNode:
    def __init__(self, node_id: str, data_path: str,
                 transport: TransportService, voting_nodes: list[str]):
        self.node_id = node_id
        self.data_path = data_path
        os.makedirs(data_path, exist_ok=True)
        self.transport = transport
        self.indices: dict[str, IndexService] = {}
        self._lock = threading.RLock()
        self.coordinator = Coordinator(
            node_id, transport, voting_nodes,
            node_info={"name": node_id}, on_apply=self._apply_state)
        t = transport
        t.register_handler(A_CREATE_INDEX, self._h_create_index)
        t.register_handler(A_DELETE_INDEX, self._h_delete_index)
        t.register_handler(A_WRITE_SHARD, self._h_write_shard)
        t.register_handler(A_GET_DOC, self._h_get_doc)
        t.register_handler(A_SEARCH_SHARDS, self._h_search_shards)
        t.register_handler(A_REFRESH, self._h_refresh)

    # -- state application (IndicesClusterStateService analog) ------------

    def _apply_state(self, state: ClusterState):
        with self._lock:
            for index, meta in state.indices.items():
                routing = state.routing.get(index, [])
                mine = [s for s, owner in enumerate(routing)
                        if owner == self.node_id]
                svc = self.indices.get(index)
                if svc is None:
                    if mine:
                        self.indices[index] = IndexService(
                            index, os.path.join(self.data_path, index),
                            dict(meta.get("settings") or {}),
                            meta.get("mappings"), local_shard_ids=mine)
                else:
                    want = set(mine)
                    have = set(svc.local_shards)
                    for s in want - have:
                        svc.add_local_shard(s)
                    for s in have - want:
                        svc.remove_local_shard(s)
            for index in list(self.indices):
                if index not in state.indices:
                    self.indices[index].close()
                    del self.indices[index]

    # -- master proxying ---------------------------------------------------

    def _master(self) -> str:
        master = self.coordinator.state().master_node
        if master is None:
            raise NoMasterError("no elected cluster manager")
        return master

    def _on_master(self, action: str, payload: dict) -> dict:
        master = self._master()
        if master == self.node_id:
            handler = {A_CREATE_INDEX: self._h_create_index,
                       A_DELETE_INDEX: self._h_delete_index}[action]
            return handler(payload)
        return self.transport.send_request(master, action, payload,
                                           timeout=10.0)

    # -- admin API ---------------------------------------------------------

    def create_index(self, name: str, body: Optional[dict] = None) -> dict:
        return self._on_master(A_CREATE_INDEX,
                               {"index": name, "body": body or {}})

    def delete_index(self, name: str) -> dict:
        return self._on_master(A_DELETE_INDEX, {"index": name})

    def _h_create_index(self, payload: dict) -> dict:
        from opensearch_tpu.common.errors import IndexAlreadyExistsError

        name = payload["index"]
        body = payload.get("body") or {}
        settings = dict(body.get("settings") or {})
        if "index" in settings:
            settings.update(settings.pop("index"))

        def update(state: ClusterState) -> ClusterState:
            if name in state.indices:
                raise IndexAlreadyExistsError(name)
            indices = dict(state.indices)
            indices[name] = {"settings": settings,
                             "mappings": body.get("mappings")}
            return allocate_shards(state.with_(indices=indices))
        self.coordinator.submit_state_update(update)
        return {"acknowledged": True, "index": name}

    def _h_delete_index(self, payload: dict) -> dict:
        name = payload["index"]

        def update(state: ClusterState) -> ClusterState:
            if name not in state.indices:
                raise IndexNotFoundError(name)
            indices = dict(state.indices)
            del indices[name]
            routing = dict(state.routing)
            routing.pop(name, None)
            return state.with_(indices=indices, routing=routing)
        self.coordinator.submit_state_update(update)
        return {"acknowledged": True}

    # -- document API ------------------------------------------------------

    def _owner(self, index: str, shard: int) -> str:
        state = self.coordinator.state()
        routing = state.routing.get(index)
        if routing is None:
            raise IndexNotFoundError(index)
        return routing[shard]

    def _shard_for(self, index: str, doc_id: str,
                   routing: Optional[str] = None) -> int:
        from opensearch_tpu.indices.service import shard_id_for
        state = self.coordinator.state()
        meta = state.indices.get(index)
        if meta is None:
            raise IndexNotFoundError(index)
        n = int((meta.get("settings") or {}).get("number_of_shards", 1))
        return shard_id_for(doc_id, routing, n)

    def index_doc(self, index: str, doc_id: str, source: dict,
                  routing: Optional[str] = None) -> dict:
        shard = self._shard_for(index, doc_id, routing)
        payload = {"index": index, "shard": shard, "op": "index",
                   "id": str(doc_id), "source": source, "routing": routing}
        owner = self._owner(index, shard)
        if owner == self.node_id:
            return self._h_write_shard(payload)
        return self.transport.send_request(owner, A_WRITE_SHARD, payload,
                                           timeout=10.0)

    def delete_doc(self, index: str, doc_id: str,
                   routing: Optional[str] = None) -> dict:
        shard = self._shard_for(index, doc_id, routing)
        payload = {"index": index, "shard": shard, "op": "delete",
                   "id": str(doc_id), "routing": routing}
        owner = self._owner(index, shard)
        if owner == self.node_id:
            return self._h_write_shard(payload)
        return self.transport.send_request(owner, A_WRITE_SHARD, payload,
                                           timeout=10.0)

    def get_doc(self, index: str, doc_id: str,
                routing: Optional[str] = None) -> Optional[dict]:
        shard = self._shard_for(index, doc_id, routing)
        owner = self._owner(index, shard)
        payload = {"index": index, "shard": shard, "id": str(doc_id)}
        if owner == self.node_id:
            resp = self._h_get_doc(payload)
        else:
            resp = self.transport.send_request(owner, A_GET_DOC, payload,
                                               timeout=10.0)
        return resp.get("doc")

    def _h_write_shard(self, payload: dict) -> dict:
        svc = self.indices.get(payload["index"])
        if svc is None:
            raise ShardNotFoundError(
                f"[{payload['index']}][{payload['shard']}] not on this node")
        engine = svc.engine_for(payload["shard"])
        if payload["op"] == "index":
            r = engine.index(payload["id"], payload["source"],
                             routing=payload.get("routing"))
        else:
            r = engine.delete(payload["id"])
        engine.ensure_synced()
        return {"_index": payload["index"], "_id": r.doc_id,
                "_version": r.version, "_seq_no": r.seq_no,
                "result": r.result, "_shard": payload["shard"]}

    def _h_get_doc(self, payload: dict) -> dict:
        svc = self.indices.get(payload["index"])
        if svc is None:
            raise ShardNotFoundError(
                f"[{payload['index']}][{payload['shard']}] not on this node")
        doc = svc.engine_for(payload["shard"]).get(payload["id"])
        return {"doc": doc}

    # -- refresh -----------------------------------------------------------

    def refresh(self, index: str):
        state = self.coordinator.state()
        if index not in state.indices:
            raise IndexNotFoundError(index)
        nodes = set(state.routing.get(index, []))
        for node in nodes:
            payload = {"index": index}
            if node == self.node_id:
                self._h_refresh(payload)
            else:
                self.transport.send_request(node, A_REFRESH, payload,
                                            timeout=10.0)

    def _h_refresh(self, payload: dict) -> dict:
        svc = self.indices.get(payload["index"])
        if svc is not None:
            svc.refresh()
        return {"ok": True}

    # -- search (scatter-gather) -------------------------------------------

    def search(self, index: str, body: Optional[dict] = None) -> dict:
        """Coordinator side: group the index's shards by owning node, one
        RPC per node, merge top-k on this node."""
        body = body or {}
        state = self.coordinator.state()
        routing = state.routing.get(index)
        if routing is None:
            raise IndexNotFoundError(index)
        by_node: dict[str, list[int]] = {}
        for shard, owner in enumerate(routing):
            by_node.setdefault(owner, []).append(shard)

        aggs_requested = bool(body.get("aggs") or body.get("aggregations"))
        if aggs_requested and len(by_node) > 1:
            # Finished per-node aggregation JSON is not mergeable (exact
            # cardinality/percentiles lose their inputs) — reject loudly
            # rather than silently dropping the aggs, matching the REST
            # controller's multi-index behavior.  Cross-node partial
            # reduce lands with mergeable sketch aggregations.
            raise ValidationError(
                "aggregations over shards on multiple nodes are not "
                "supported yet — shrink the index to one node or drop "
                "the aggs clause")

        size = int(body.get("size", 10))
        from_ = int(body.get("from", 0))
        sub = dict(body)
        sub["from"] = 0
        sub["size"] = from_ + size

        responses = []
        futures = []
        for node, shards in by_node.items():
            payload = {"index": index, "shards": shards, "body": sub}
            if node == self.node_id:
                responses.append(self._h_search_shards(payload))
            else:
                futures.append(self.transport.submit_request(
                    node, A_SEARCH_SHARDS, payload))
        for fut in futures:
            responses.append(fut.result(timeout=30.0))

        all_hits = []
        total = 0
        max_score = None
        rows = []
        for node_idx, resp in enumerate(responses):
            r = resp["resp"]
            for pos, h in enumerate(r["hits"]["hits"]):
                rows.append((h, node_idx, pos))
            total += r["hits"]["total"]["value"]
            ms = r["hits"]["max_score"]
            if ms is not None and (max_score is None or ms > max_score):
                max_score = ms
        all_hits = merge_hit_rows(rows, body.get("sort"))
        n_shards = len(routing)
        out = {
            "took": max((resp["resp"]["took"] for resp in responses),
                        default=0),
            "timed_out": False,
            "_shards": {"total": n_shards, "successful": n_shards,
                        "skipped": 0, "failed": 0},
            "hits": {"total": {"value": total, "relation": "eq"},
                     "max_score": max_score,
                     "hits": all_hits[from_: from_ + size]},
        }
        if aggs_requested and len(responses) == 1:
            # single data node computed the full aggregation — passthrough
            out["aggregations"] = responses[0]["resp"].get("aggregations")
        return out

    def _h_search_shards(self, payload: dict) -> dict:
        svc = self.indices.get(payload["index"])
        if svc is None:
            raise ShardNotFoundError(
                f"[{payload['index']}] has no shards on this node")
        from opensearch_tpu.search.executor import ShardSearcher
        segs = []
        for shard_id in payload["shards"]:
            engine = svc.engine_for(shard_id)
            segs.extend(engine.acquire_searcher().segments)
        searcher = ShardSearcher(segs, svc.mapper, index_name=svc.name)
        return {"resp": searcher.search(payload.get("body") or {})}

    # -- lifecycle ---------------------------------------------------------

    def start_election(self) -> bool:
        return self.coordinator.start_election()

    def start(self):
        self.coordinator.start()
        return self

    def stop(self):
        self.coordinator.stop()
        with self._lock:
            for svc in self.indices.values():
                svc.close()
            self.indices.clear()
        self.transport.close()
