"""ClusterNode: a full cluster member — coordinator + transport +
shard-subset indices + distributed document/search actions.

Analog of the action layer (L6) on the cluster runtime (L4):

- index admin ops proxy to the elected cluster-manager, which mutates the
  cluster state and publishes (TransportCreateIndexAction ->
  MetadataCreateIndexService -> MasterService, call stack SURVEY §3.4);
- applied states create/remove LOCAL shards per the routing table
  (indices/cluster/IndicesClusterStateService.java);
- document ops route by murmur3 to the owning node
  (TransportBulkAction :213 grouping / OperationRouting);
- search scatter-gathers: shards grouped per node, one RPC each, host
  merge of top-k (AbstractSearchAsyncAction :223 + SearchPhaseController
  merge).  Per-shard scoring stats, like the reference's default
  query_then_fetch.
"""

from __future__ import annotations

import os
import threading
import time
import zlib
from concurrent.futures import TimeoutError as FuturesTimeout
from typing import Optional

from opensearch_tpu.search import insights as insights_mod
from opensearch_tpu.search.executor import merge_hit_rows

from opensearch_tpu.common.errors import (
    IndexNotFoundError,
    NodeDisconnectedError,
    OpenSearchTpuError,
    PrimaryFencedError,
    ShardNotFoundError,
    VersionConflictError,
)
from opensearch_tpu.common.fshealth import FsHealthService
from opensearch_tpu.common.retry import retry_call
from opensearch_tpu.cluster.coordination import CoordinationError, Coordinator
from opensearch_tpu.cluster.state import (ClusterState, allocate_shards,
                                          copies_of, search_copies_of)
from opensearch_tpu.index.store import CorruptIndexError
from opensearch_tpu.indices.service import IndexService
from opensearch_tpu.transport.service import (ReceiveTimeoutError,
                                              RemoteTransportError,
                                              TransportService)

# remote error types that are the CLIENT's fault: failing over to
# another copy would just repeat the same deterministic rejection, so
# these re-raise instead of degrading to a counted shard failure
_CLIENT_ERROR_TYPES = frozenset({
    "parsing_exception", "illegal_argument_exception",
    "action_request_validation_exception", "mapper_parsing_exception",
    "index_not_found_exception"})


def _degradable_search_error(exc: BaseException) -> bool:
    """Is this shard-level failure one the coordinator may paper over
    (retry the next copy / count in ``_shards.failed``)?"""
    from opensearch_tpu.common import breakers
    from opensearch_tpu.common.device_health import DeviceDegradedError
    from opensearch_tpu.common.errors import CircuitBreakingError
    from opensearch_tpu.common.tasks import TaskCancelledException

    # a shard task cancelled under it (backpressure duress, parent ban)
    # degrades to a counted failure: the coordinator returns the partial
    # results it has instead of hanging or failing the whole search.
    # A locally-poisoned copy (CorruptIndexError) fails over the same
    # way a remote one does — another copy has the data.  A copy whose
    # ACCELERATOR is misbehaving (DeviceDegradedError: open device
    # breaker / dispatch fault with no host fallback) degrades the same
    # way — another copy's device may be healthy
    if isinstance(exc, (NodeDisconnectedError, ReceiveTimeoutError,
                        ShardNotFoundError, CircuitBreakingError,
                        breakers.CircuitBreakingError,
                        CorruptIndexError, DeviceDegradedError,
                        TaskCancelledException)):
        return True
    if isinstance(exc, RemoteTransportError):
        return exc.remote_type not in _CLIENT_ERROR_TYPES
    return False

A_CREATE_INDEX = "cluster:admin/index/create"
A_DELETE_INDEX = "cluster:admin/index/delete"
A_WRITE_SHARD = "indices:data/write/shard"
A_GET_DOC = "indices:data/read/get"
A_SEARCH_SHARDS = "indices:data/read/search[shards]"
A_REFRESH = "indices:admin/refresh"
# replication + recovery (ReplicationOperation / SegmentReplication /
# PeerRecovery action families)
A_REPLICATE_OP = "indices:data/write/shard[r]"
# promotion resync: the new primary rolls in-sync peers back above the
# old global checkpoint and replays its retained ops under the bumped
# term (PrimaryReplicaSyncer / TransportResyncReplicationAction)
A_RESYNC = "indices:data/write/shard[resync]"
A_PUBLISH_CKPT = "indices:admin/replication/checkpoint"
A_FETCH_SEGMENTS = "indices:admin/replication/segments"
A_START_RECOVERY = "internal:index/shard/recovery/start"
A_FAIL_COPY = "internal:cluster/shard/failure"
A_SHARD_RECOVERED = "internal:cluster/shard/started"
# parent-task ban broadcast (TaskCancellationService's
# internal:admin/tasks/ban): a cancelled coordinator search reaps its
# remote shard tasks instead of leaving them running
A_BAN_PARENT = "internal:admin/tasks/ban"
A_INSIGHTS = "cluster:monitor/insights/top_queries"
# search-replica tier (segment replication over the remote store):
# primaries publish checkpoints NAMING remote blob digests; searchers
# install by pulling from the blob store — never from the primary —
# and report refill completion to the cluster manager
A_PUBLISH_SEARCH_CKPT = "indices:admin/replication/search_checkpoint"
A_SEARCH_SHARD_READY = "internal:cluster/shard/search_ready"
A_UPDATE_SETTINGS = "cluster:admin/index/settings"

#: transport actions that mutate shard state — a search-role node must
#: reject (or leave unregistered) every one of them; enforced by
#: tools/check_searcher_write_isolation.py (tier-1).  Every handler
#: registered under these actions must also fence by primary term
#: against cluster state (tools/check_term_fencing.py, tier-1)
WRITE_ACTIONS = (A_WRITE_SHARD, A_REPLICATE_OP, A_RESYNC)


class NoMasterError(CoordinationError):
    status = 503


class ClusterNode:
    def __init__(self, node_id: str, data_path: str,
                 transport: TransportService, voting_nodes: list[str],
                 roles: tuple = ("master", "data"),
                 remote_store_path: Optional[str] = None,
                 file_cache_bytes: int = 256 << 20):
        self.node_id = node_id
        self.data_path = data_path
        os.makedirs(data_path, exist_ok=True)
        self.transport = transport
        # node roles (the reference's node.roles): "data" nodes hold
        # write copies and serve replication; "search" nodes hold only
        # search replicas refilled from the remote store; "master"
        # grants election eligibility.  Search-only nodes are stateless
        # over the blob store — kill one and its replacement recovers
        # by cache refill, never by contacting a primary.
        self.roles = tuple(roles)
        self.is_data = "data" in self.roles
        self.is_search = "search" in self.roles
        # the shared blob repository backing the search tier (and any
        # remote-store mirroring): every node of the cluster points at
        # the same store, the way every reference node names the same
        # S3 bucket
        self.remote_store = None
        self.file_cache = None
        if remote_store_path:
            from opensearch_tpu.snapshots.service import Repository
            self.remote_store = Repository(
                "cluster-remote", "fs", {"location": remote_store_path})
            if self.is_search:
                from opensearch_tpu.index.filecache import FileCache
                self.file_cache = FileCache(
                    os.path.join(data_path, "filecache"),
                    file_cache_bytes)
        # (index, shard) -> highest checkpoint seq published / installed
        # on THIS searcher; the difference is the replication lag
        # piggybacked on pings and bounded by search.replication.max_lag
        self._search_published: dict[tuple, int] = {}
        self._search_installed: dict[tuple, int] = {}
        # peer-recovery / segment-fetch RPC budget (tests shrink it so
        # timeout paths stay fast) — satellite fix: _h_publish_ckpt used
        # to hardcode 30s with no retry
        self.recovery_timeout = 30.0
        self.indices: dict[str, IndexService] = {}
        # every shard-level search runs as a registered, cancellable
        # task with a parent id (the coordinator's), so _tasks-style
        # cancellation and backpressure reach remote work
        from opensearch_tpu.common.tasks import TaskManager
        from opensearch_tpu.search.backpressure import \
            SearchBackpressureService
        self.task_manager = TaskManager(node_id)
        self.search_backpressure = SearchBackpressureService(
            self.task_manager)
        # coordinator-side adaptive replica selection: per-node response/
        # service/queue EWMAs fed by scatter responses and fault-detection
        # pings (cluster/response_collector.py)
        from opensearch_tpu.cluster.response_collector import (
            Ewma, ResponseCollectorService)
        self.response_collector = ResponseCollectorService()
        # this node's own query-phase service time, piggybacked on every
        # search[shards] response and ping so coordinators can separate
        # execution cost from queueing/transport delay
        self._service_time_ewma = Ewma()
        # shard query-phase RPC budget (tests shrink it so timeout-path
        # assertions stay fast)
        self.search_rpc_timeout = 30.0
        # always-on query insights: this node records both the shard
        # query phases it executes (data-node role) and the scatters it
        # coordinates; top_queries() below fans the sections in
        from opensearch_tpu.search.insights import QueryInsightsService
        self.insights = QueryInsightsService(node_id=node_id)
        # per-tenant QoS + adaptive overload control: the AIMD
        # controller closing the loop between this node's admission
        # ledger / flight-recorder breaches / insights coalescability
        # and the shed-occupancy, batcher-window, and tenant-share
        # knobs (search/qos.py; off until search.qos.adaptive)
        from opensearch_tpu.search.qos import QosController
        self.qos = QosController(
            admission=self.search_backpressure.admission,
            insights=self.insights,
            backpressure=self.search_backpressure)
        # data-node write admission (the same per-shard byte accounting
        # the single-node path gets from IndicesService)
        from opensearch_tpu.common.indexing_pressure import IndexingPressure
        self.indexing_pressure = IndexingPressure(
            int(os.environ.get("OSTPU_INDEXING_PRESSURE_LIMIT", 64 << 20)))
        self._lock = threading.RLock()
        # disk-health probe: its verdict piggybacks on fault-detection
        # pings (leader evicts an unhealthy data node) and gates this
        # node's own election eligibility (FsHealthService wiring)
        self.fs_health = FsHealthService(data_path)
        self.fs_health_interval = 5.0
        from opensearch_tpu.cluster.gateway import GatewayStateStore
        self.gateway = GatewayStateStore(os.path.join(data_path, "_state"))
        # legacy full-role nodes keep the bare info shape (states stay
        # byte-identical for existing clusters); non-default roles are
        # published so the allocator can tell tiers apart
        node_info = {"name": node_id}
        if set(self.roles) != {"master", "data"}:
            node_info["roles"] = list(self.roles)
            node_info["master_eligible"] = "master" in self.roles
        self.coordinator = Coordinator(
            node_id, transport, voting_nodes,
            node_info=node_info, on_apply=self._apply_state,
            gateway=self.gateway,
            load_provider=self._load_stats,
            on_node_load=self.response_collector.record_ping_load,
            health_provider=lambda: self.fs_health.healthy)
        # C3 ranks into write routing: allocation closures executed by
        # this node (as leader) break least-loaded ties with the local
        # collector's health evidence
        self.coordinator.rank_fn = self.response_collector.rank
        # QoS-driven searcher elasticity: the leader-side control loop
        # from admission/Retry-After evidence to fleet mutation
        # (cluster/autoscaler.py; inert until cluster.autoscale.enabled
        # and a provisioner is wired by the environment)
        from opensearch_tpu.cluster.autoscaler import SearcherAutoscaler
        self.autoscaler = SearcherAutoscaler(
            self.coordinator,
            admission=self.search_backpressure.admission,
            collector=self.response_collector,
            qos=self.qos)
        # (index, shard) -> "primary" | "replica" as applied locally
        self._roles: dict[tuple, str] = {}
        # primary-side per-copy local checkpoints, (index, shard) ->
        # {replica node -> highest reported local checkpoint}
        # (ReplicationTracker's CheckpointState): min over the in-sync
        # set is the global checkpoint piggybacked on replication ops
        self._local_ckpts: dict[tuple, dict[str, int]] = {}
        # (index, shard) replica copies that completed peer recovery in
        # THIS process (an engine reopened after restart must re-recover)
        self._recovered: set[tuple] = set()
        self._recovering: set[tuple] = set()
        # (index, shard) copies whose corruption failover is in flight:
        # every applied state re-sees the poisoned engine until the
        # reset lands, and a second handler's reset would wipe the
        # re-recovered copy
        self._corrupt_handling: set[tuple] = set()
        t = transport
        t.register_handler(A_CREATE_INDEX, self._h_create_index)
        t.register_handler(A_DELETE_INDEX, self._h_delete_index)
        t.register_handler(A_UPDATE_SETTINGS, self._h_update_settings)
        t.register_handler(A_GET_DOC, self._h_get_doc)
        t.register_handler(A_SEARCH_SHARDS, self._h_search_shards)
        t.register_handler(A_REFRESH, self._h_refresh)
        self._register_write_handlers(t)
        t.register_handler(A_FAIL_COPY, self._h_fail_copy)
        t.register_handler(A_SHARD_RECOVERED, self._h_shard_recovered)
        t.register_handler(A_SEARCH_SHARD_READY,
                           self._h_search_shard_ready)
        t.register_handler(A_PUBLISH_SEARCH_CKPT,
                           self._h_publish_search_ckpt)
        t.register_handler(A_BAN_PARENT, self._h_ban_parent)
        t.register_handler(A_INSIGHTS, self._h_insights)
        # restart: reopen local shards from the restored committed state
        # right away (the GatewayAllocator's on-disk-copy path) so engines
        # replay their translogs before any routing decisions arrive.
        # recover=False: replica resync waits for the first post-election
        # committed state — at construction time peer transports aren't
        # registered yet, and the resync belongs to the live cluster
        restored = self.coordinator.state()
        if restored.indices:
            self._apply_state(restored, recover=False)

    # -- write-path isolation (search-role nodes) --------------------------

    def _register_write_handlers(self, t: TransportService):
        """The write/replication transport surface, registered ONLY on
        data-role nodes.  A search-only node registers a rejecting stub
        for every ``WRITE_ACTIONS`` entry — a misrouted write fails loud
        with a clear verdict instead of silently mutating searcher
        state — and leaves the peer-recovery / segment-fetch family
        unregistered entirely (searchers are never a recovery source).
        ``tools/check_searcher_write_isolation.py`` (tier-1) pins write
        registrations to this method."""
        write_handlers = {A_WRITE_SHARD: self._h_write_shard,
                          A_REPLICATE_OP: self._h_replicate_op,
                          A_RESYNC: self._h_resync}
        assert set(write_handlers) == set(WRITE_ACTIONS)
        for action, handler in write_handlers.items():
            if self.is_data:
                t.register_handler(action, handler)
            else:
                t.register_handler(action, self._reject_write(action))
        if self.is_data:
            t.register_handler(A_PUBLISH_CKPT, self._h_publish_ckpt)
            t.register_handler(A_FETCH_SEGMENTS, self._h_fetch_segments)
            t.register_handler(A_START_RECOVERY, self._h_start_recovery)

    def _reject_write(self, action: str):
        from opensearch_tpu.common.errors import IllegalArgumentError

        def handler(payload: dict) -> dict:
            raise IllegalArgumentError(
                f"node [{self.node_id}] has roles {list(self.roles)}: "
                f"write action [{action}] is rejected on the search "
                "tier")
        return handler

    # -- state application (IndicesClusterStateService analog) ------------

    # remove_node below is the C3 stats tombstone, not fleet membership;
    # actuator-ok (reacting to a membership change its committer audited)
    def _apply_state(self, state: ClusterState, recover: bool = True):
        # handshake newly-seen peers in the background: the negotiated
        # protocol version is cached per peer and an incompatible major
        # is logged (the TransportHandshaker-on-connect analog)
        for peer in state.nodes:
            if (peer != self.node_id
                    and peer not in self.transport._peer_versions):
                threading.Thread(target=self._handshake_peer,
                                 args=(peer,), daemon=True,
                                 name=f"handshake-{self.node_id}-{peer}"
                                 ).start()
        # evicted nodes take their adaptive-selection stats with them —
        # a rejoining node starts from a clean slate, not a stale EWMA.
        # remove_node leaves a tombstone so a late in-flight response
        # cannot resurrect the evicted entry (stale duress flag with a
        # refreshed TTL included); present nodes clear their tombstone
        for gone in self.response_collector.tracked() - set(state.nodes):
            self.response_collector.remove_node(gone)
        for present in state.nodes:
            self.response_collector.readmit(present)
        to_promote: list[tuple] = []
        to_demote: list[tuple] = []
        to_recover: list[tuple] = []
        to_refill: list[tuple] = []
        to_fail_corrupt: list[tuple] = []
        with self._lock:
            for index, meta in state.indices.items():
                routing = state.routing.get(index, [])
                mine: dict[int, str] = {}
                for s, entry in enumerate(routing):
                    if entry.get("primary") == self.node_id:
                        mine[s] = "primary"
                    elif self.node_id in (entry.get("replicas") or []):
                        mine[s] = "replica"
                    elif self.node_id in (entry.get("search_replicas")
                                          or []):
                        mine[s] = "search"
                svc = self.indices.get(index)
                if svc is None:
                    if mine:
                        svc = IndexService(
                            index, os.path.join(self.data_path, index),
                            dict(meta.get("settings") or {}),
                            meta.get("mappings"),
                            local_shard_ids=sorted(mine))
                        svc.indexing_pressure = self.indexing_pressure
                        self.indices[index] = svc
                else:
                    want = set(mine)
                    have = set(svc.local_shards)
                    for s in want - have:
                        svc.add_local_shard(s)
                    for s in have - want:
                        svc.remove_local_shard(s)
                        self._roles.pop((index, s), None)
                        self._recovered.discard((index, s))
                        self._local_ckpts.pop((index, s), None)
                        self._search_published.pop((index, s), None)
                        self._search_installed.pop((index, s), None)
                for s, role in mine.items():
                    entry = routing[s]
                    prev = self._roles.get((index, s))
                    self._roles[(index, s)] = role
                    engine = svc.local_shards.get(s)
                    if role == "search":
                        # search-only copy: stateless over the remote
                        # store — every install path is a cache refill,
                        # including the corruption case (_on_corruption
                        # resets + re-pulls, no A_FAIL_COPY round-trip)
                        if engine is not None:
                            engine.search_only = True
                        if ((index, s) not in self._recovered
                                and (index, s) not in self._recovering):
                            self._recovering.add((index, s))
                            to_refill.append((index, s))
                        continue
                    if (engine is not None
                            and engine.corruption is not None
                            and (index, s) not in self._corrupt_handling):
                        # a copy that failed store verification at open
                        # (restart over bit rot) runs the corruption
                        # failover instead of serving errors forever
                        to_fail_corrupt.append((index, s,
                                                engine.corruption))
                        continue
                    if role == "primary":
                        if prev == "replica":
                            # failover promotion: replay buffered ops
                            # under the bumped term (fencing)
                            to_promote.append(
                                (index, s, entry["primary_term"]))
                        self._recovered.add((index, s))
                    elif role == "replica":
                        if prev == "primary":
                            # deposed primary rejoining as a replica:
                            # its ops above the global checkpoint may
                            # diverge from the new lineage — roll them
                            # back (below, before recovery threads
                            # start) and force a fresh peer recovery
                            # under the new term
                            self._recovered.discard((index, s))
                            self._local_ckpts.pop((index, s), None)
                            to_demote.append((index, s))
                        if (recover
                                and (index, s) not in self._recovered
                                and (index, s) not in self._recovering
                                and entry.get("primary")):
                            self._recovering.add((index, s))
                            to_recover.append(
                                (index, s, entry["primary"],
                                 self._recovery_source(entry)))
            for index in list(self.indices):
                if index not in state.indices:
                    self.indices[index].close()
                    del self.indices[index]
                    for key in [k for k in self._roles if k[0] == index]:
                        del self._roles[key]
                        self._recovered.discard(key)
        for index, s in to_demote:
            # rollback BEFORE recovery threads start: ops-mode recovery
            # from an inflated _seq_no would otherwise freeze the
            # divergence in forever (trimOperationsOfPreviousPrimaryTerms)
            try:
                eng = self.indices[index].engine_for(s)
                rolled = eng.rollback_above(eng.global_checkpoint)
                if rolled:
                    from opensearch_tpu.common.telemetry import (
                        flight_recorder, metrics)
                    metrics().counter("replication.rollbacks").inc()
                    flight_recorder().record(
                        "demotion_rollback",
                        f"[{index}][{s}] deposed primary rolled back "
                        f"{rolled} divergent op(s) above global "
                        f"checkpoint {eng.global_checkpoint}",
                        detail={"index": index, "shard": s,
                                "rolled_back": rolled,
                                "global_checkpoint":
                                    eng.global_checkpoint})
            except OpenSearchTpuError:
                pass
        for index, s, term in to_promote:
            try:
                self.indices[index].engine_for(s).promote_to_primary(term)
            except OpenSearchTpuError:
                pass
            threading.Thread(
                target=self._run_primary_resync, args=(index, s, term),
                daemon=True,
                name=f"resync-{self.node_id}-{index}-{s}").start()
        for index, s, primary, source in to_recover:
            threading.Thread(
                target=self._run_recovery,
                args=(index, s, primary, source),
                daemon=True,
                name=f"recovery-{self.node_id}-{index}-{s}").start()
        for index, s in to_refill:
            threading.Thread(
                target=self._run_searcher_recovery, args=(index, s),
                daemon=True,
                name=f"refill-{self.node_id}-{index}-{s}").start()
        for index, s, exc in to_fail_corrupt:
            threading.Thread(
                target=self._on_corruption, args=(index, s, exc),
                daemon=True,
                name=f"corruption-{self.node_id}-{index}-{s}").start()

    # -- peer recovery (replica side) -------------------------------------

    def _recovery_source(self, entry: dict) -> str:
        """Pick the recovery source by C3 rank: the least-loaded
        in-sync copy (PR 6's explicit leftover — recovery file copy is
        the heaviest read a copy can serve, so it should come off the
        copy with the most headroom, not always the primary).  With no
        response evidence the stable rank preserves the legacy order —
        primary first; the primary stays the fallback either way (see
        ``_run_recovery``)."""
        primary = entry.get("primary")
        in_sync = set(entry.get("in_sync") or [])
        sources = [n for n in ([primary] if primary else [])
                   + list(entry.get("replicas") or [])
                   if n in in_sync and n != self.node_id]
        if len(sources) < 2:
            return primary
        ranked, _ = self.response_collector.rank_copies(sources)
        return ranked[0] if ranked else primary

    def _run_recovery(self, index: str, shard: int, primary: str,
                      source: Optional[str] = None):
        """Bootstrap this node's replica copy from the C3-ranked
        recovery source (least-loaded in-sync copy; the primary with no
        evidence): segment file copy (phase 1; phase-2 op replay is
        subsumed by the live A_REPLICATE_OP stream that started when
        the copy was assigned), then report recovered so the master
        adds us to the in-sync set (ref
        indices/recovery/RecoverySourceHandler.java:105,
        ReplicationTracker.markAllocationIdAsInSync:1533).  A ranked
        non-primary source that fails falls back to the primary before
        the recovery gives up to the next state application."""
        from opensearch_tpu.common.telemetry import metrics
        # source order: the ranked pick first, the primary as fallback
        sources = ([source] if source and source != primary else []) \
            + [primary]
        try:
            svc = self.indices.get(index)
            local_ckpt = -1
            if svc is not None:
                # offer op-based recovery: our highest applied seq-no
                local_ckpt = svc.engine_for(shard)._seq_no
            for install_attempt in range(3):
                # transient drops during recovery retry in place:
                # restarting the whole recovery from the next
                # cluster-state application is far more expensive than
                # one more RPC
                src = sources[0]
                try:
                    resp = retry_call(
                        "recovery.start",
                        lambda src=src: self.transport.send_request(
                            src, A_START_RECOVERY,
                            {"index": index, "shard": shard,
                             "node": self.node_id,
                             "local_checkpoint": local_ckpt},
                            timeout=30.0),
                        max_attempts=3, base_delay=0.1, max_delay=1.0,
                        budget_s=90.0, seed=zlib.crc32(
                            f"{self.node_id}/{index}/{shard}".encode()))
                except OpenSearchTpuError:
                    if len(sources) > 1:
                        # the ranked source failed its whole retry
                        # budget: fall back to the primary, counted
                        metrics().counter(
                            "recovery.source_fallbacks").inc()
                        sources.pop(0)
                        continue
                    raise
                svc = self.indices.get(index)
                if svc is None:
                    return
                engine = svc.engine_for(shard)
                try:
                    if resp.get("mode") == "ops":
                        # retention-lease fast path: replay the missed
                        # ops, no file copy (RecoverySourceHandler
                        # phase-2-only recovery)
                        for op in resp["ops"]:
                            engine.apply_replica_op(op)
                        engine.refresh()
                    else:
                        engine.install_checkpoint(resp["ckpt"],
                                                  resp["blobs"])
                    break
                except CorruptIndexError:
                    # a blob damaged in flight (or on the primary's way
                    # out) must be RE-REQUESTED, not installed: the
                    # verify in segment_from_blobs already rejected it
                    # before any engine state changed
                    metrics().counter("recovery.corrupt_blobs").inc()
                    if install_attempt == 2:
                        raise
            svc.invalidate_searcher()
            master = self._master()
            payload = {"index": index, "shard": shard,
                       "node": self.node_id}
            if master == self.node_id:
                self._h_shard_recovered(payload)
            else:
                retry_call(
                    "recovery.report",
                    lambda: self.transport.send_request(
                        master, A_SHARD_RECOVERED, payload, timeout=10.0),
                    max_attempts=2, base_delay=0.05,
                    seed=zlib.crc32(self.node_id.encode()))
            with self._lock:
                self._recovered.add((index, shard))
        except OpenSearchTpuError:
            pass   # next cluster-state application retries
        finally:
            with self._lock:
                self._recovering.discard((index, shard))

    def _h_start_recovery(self, payload: dict) -> dict:
        """Primary side: if a retention lease covers the replica's local
        checkpoint, ship just the missed ops (no file copy —
        index/seqno/RetentionLease); otherwise refresh and ship the full
        segment set."""
        svc = self.indices.get(payload["index"])
        if svc is None:
            raise ShardNotFoundError(
                f"[{payload['index']}][{payload['shard']}] not on this node")
        engine = svc.engine_for(payload["shard"])
        replica = payload.get("node")
        local_ckpt = int(payload.get("local_checkpoint", -1))
        if replica is not None and local_ckpt >= 0:
            ops = engine.ops_since(local_ckpt)
            if ops is not None:
                # renew the lease at the replica's NEW checkpoint
                engine.add_retention_lease(replica, engine._seq_no)
                self._track_replica_ckpt(payload["index"],
                                         payload["shard"], replica,
                                         engine._seq_no)
                return {"mode": "ops", "ops": ops,
                        "max_seq_no": engine._seq_no}
        engine.refresh()
        if replica is not None:
            # track the copy from here on so its next recovery can be
            # ops-based; seed its local checkpoint at what we ship
            engine.add_retention_lease(replica, engine._seq_no)
            self._track_replica_ckpt(payload["index"], payload["shard"],
                                     replica, engine._seq_no)
        ckpt = engine.checkpoint_info()
        return {"ckpt": ckpt, "blobs": engine.segments_blobs(ckpt["segments"])}

    def _h_shard_recovered(self, payload: dict) -> dict:  # actuator-ok (in-sync bookkeeping, not fleet/QoS actuation)
        index, shard, node = (payload["index"], payload["shard"],
                              payload["node"])

        def update(state: ClusterState) -> ClusterState:
            routing = {k: [dict(e) for e in v]
                       for k, v in state.routing.items()}
            entries = routing.get(index)
            if entries is None or shard >= len(entries):
                return state
            e = entries[shard]
            if node in (e.get("replicas") or []) and node not in e["in_sync"]:
                e["in_sync"] = list(e["in_sync"]) + [node]
                return state.with_(routing=routing)
            return state
        self.coordinator.submit_state_update(update)
        return {"acknowledged": True}

    def _h_fail_copy(self, payload: dict) -> dict:  # actuator-ok (fault eviction of a shard copy, not a policy decision)
        """Master: drop a failed shard copy from the group and
        re-allocate a replacement (ReplicationOperation's fail-shard call
        to the cluster manager).  A failed PRIMARY promotes an in-sync
        replica under a bumped term — the old lineage is fenced out —
        in two cases: corruption (copy dropped entirely) and a
        ``deposed`` self-report (the primary saw a fence rejection and
        stopped acking; its copy stays assigned as an OUT-of-sync
        replica that rolls back and re-recovers).  With no safe copy to
        promote, corruption flags the group red; a deposed report is a
        no-op (the reporter may in fact be the only viable primary)."""
        index, shard, node = (payload["index"], payload["shard"],
                              payload["node"])

        def update(state: ClusterState) -> ClusterState:
            routing = {k: [dict(e) for e in v]
                       for k, v in state.routing.items()}
            entries = routing.get(index)
            if entries is None or shard >= len(entries):
                return state
            e = entries[shard]
            if node == e.get("primary"):
                deposed = bool(payload.get("deposed"))
                if not payload.get("corrupted") and not deposed:
                    # only corruption/deposition fails a live primary
                    return state
                safe = [r for r in (e.get("replicas") or [])
                        if r in (e.get("in_sync") or []) and r != node]
                if not safe:
                    if deposed:
                        return state
                    # nothing safe to promote: keep the copy (its data,
                    # corrupt as it is, is all that exists) but mark the
                    # group so health goes red instead of lying green
                    e["corrupted"] = True
                    return state.with_(routing=routing)
                promo = safe[0]
                e["primary"] = promo
                e["replicas"] = [r for r in e["replicas"] if r != promo]
                if deposed:
                    # the deposed copy keeps a slot, out of in-sync: it
                    # rolls back above the global checkpoint on applying
                    # this state and peer-recovers under the new term
                    e["replicas"] = list(e["replicas"]) + [node]
                e["in_sync"] = [n for n in e["in_sync"]
                                if n != node and n in (
                                    [promo] + e["replicas"])]
                e["primary_term"] = int(e.get("primary_term", 1)) + 1
                e.pop("corrupted", None)
                return allocate_shards(state.with_(routing=routing),
                                       rank=self.response_collector.rank)
            if node not in (e.get("replicas") or []):
                return state
            e["replicas"] = [r for r in e["replicas"] if r != node]
            e["in_sync"] = [n for n in e["in_sync"] if n != node]
            return allocate_shards(state.with_(routing=routing),
                                   rank=self.response_collector.rank)
        self.coordinator.submit_state_update(update)
        # a permanently-failed copy releases its retention lease so the
        # primary's translog can trim again (RetentionLease expiry)
        svc = self.indices.get(index)
        if svc is not None:
            try:
                svc.engine_for(shard).remove_retention_lease(node)
            except OpenSearchTpuError:
                pass
        return {"acknowledged": True}

    # -- master proxying ---------------------------------------------------

    def _master(self) -> str:
        master = self.coordinator.state().master_node
        if master is None:
            raise NoMasterError("no elected cluster manager")
        return master

    def _on_master(self, action: str, payload: dict) -> dict:
        master = self._master()
        if master == self.node_id:
            handler = {A_CREATE_INDEX: self._h_create_index,
                       A_DELETE_INDEX: self._h_delete_index,
                       A_UPDATE_SETTINGS: self._h_update_settings}[action]
            return handler(payload)
        return self.transport.send_request(master, action, payload,
                                           timeout=10.0)

    # -- admin API ---------------------------------------------------------

    def create_index(self, name: str, body: Optional[dict] = None) -> dict:
        return self._on_master(A_CREATE_INDEX,
                               {"index": name, "body": body or {}})

    def delete_index(self, name: str) -> dict:
        return self._on_master(A_DELETE_INDEX, {"index": name})

    def update_index_settings(self, name: str,
                              settings: Optional[dict] = None) -> dict:
        """Live index-settings update (the `_settings` API at cluster
        scope).  ``number_of_search_replicas`` scales the searcher
        fleet elastically: raising it allocates fresh search slots that
        refill from the remote store (zero reindexing, zero primary
        involvement); lowering it drops slots on the next applied
        state.  ``number_of_replicas`` re-allocates the write tier the
        same way; ``number_of_shards`` is immutable like the
        reference's."""
        return self._on_master(A_UPDATE_SETTINGS,
                               {"index": name,
                                "settings": settings or {}})

    def _h_update_settings(self, payload: dict) -> dict:  # actuator-ok (operator-initiated settings, not fleet/QoS actuation)
        from opensearch_tpu.common.errors import IllegalArgumentError

        name = payload["index"]
        ups = dict(payload.get("settings") or {})
        if "index" in ups and isinstance(ups["index"], dict):
            ups.update(ups.pop("index"))
        if "number_of_shards" in ups:
            raise IllegalArgumentError(
                "final index setting [number_of_shards] cannot be "
                "updated on a live index")

        def update(state: ClusterState) -> ClusterState:
            if name not in state.indices:
                raise IndexNotFoundError(name)
            indices = dict(state.indices)
            meta = dict(indices[name])
            meta["settings"] = {**(meta.get("settings") or {}), **ups}
            indices[name] = meta
            return allocate_shards(state.with_(indices=indices),
                                   rank=self.response_collector.rank)
        self.coordinator.submit_state_update(update)
        return {"acknowledged": True}

    def _h_create_index(self, payload: dict) -> dict:  # actuator-ok (operator-initiated metadata, not fleet/QoS actuation)
        from opensearch_tpu.common.errors import IndexAlreadyExistsError

        name = payload["index"]
        body = payload.get("body") or {}
        settings = dict(body.get("settings") or {})
        if "index" in settings:
            settings.update(settings.pop("index"))

        def update(state: ClusterState) -> ClusterState:
            if name in state.indices:
                raise IndexAlreadyExistsError(name)
            indices = dict(state.indices)
            indices[name] = {"settings": settings,
                             "mappings": body.get("mappings")}
            return allocate_shards(state.with_(indices=indices),
                                   rank=self.response_collector.rank)
        self.coordinator.submit_state_update(update)
        return {"acknowledged": True, "index": name}

    def _h_delete_index(self, payload: dict) -> dict:  # actuator-ok (operator-initiated metadata, not fleet/QoS actuation)
        name = payload["index"]

        def update(state: ClusterState) -> ClusterState:
            if name not in state.indices:
                raise IndexNotFoundError(name)
            indices = dict(state.indices)
            del indices[name]
            routing = dict(state.routing)
            routing.pop(name, None)
            return state.with_(indices=indices, routing=routing)
        self.coordinator.submit_state_update(update)
        return {"acknowledged": True}

    # -- document API ------------------------------------------------------

    def _entry(self, index: str, shard: int) -> dict:
        state = self.coordinator.state()
        routing = state.routing.get(index)
        if routing is None:
            raise IndexNotFoundError(index)
        return routing[shard]

    def _owner(self, index: str, shard: int) -> str:
        """The primary copy's node — all writes route here."""
        primary = self._entry(index, shard).get("primary")
        if primary is None:
            raise ShardNotFoundError(
                f"[{index}][{shard}] has no assigned primary")
        return primary

    def _shard_for(self, index: str, doc_id: str,
                   routing: Optional[str] = None) -> int:
        from opensearch_tpu.indices.service import shard_id_for
        state = self.coordinator.state()
        meta = state.indices.get(index)
        if meta is None:
            raise IndexNotFoundError(index)
        n = int((meta.get("settings") or {}).get("number_of_shards", 1))
        return shard_id_for(doc_id, routing, n)

    def index_doc(self, index: str, doc_id: str, source: dict,
                  routing: Optional[str] = None) -> dict:
        shard = self._shard_for(index, doc_id, routing)
        payload = {"index": index, "shard": shard, "op": "index",
                   "id": str(doc_id), "source": source, "routing": routing}
        owner = self._owner(index, shard)
        if owner == self.node_id:
            return self._h_write_shard(payload)
        return self.transport.send_request(owner, A_WRITE_SHARD, payload,
                                           timeout=10.0)

    def delete_doc(self, index: str, doc_id: str,
                   routing: Optional[str] = None) -> dict:
        shard = self._shard_for(index, doc_id, routing)
        payload = {"index": index, "shard": shard, "op": "delete",
                   "id": str(doc_id), "routing": routing}
        owner = self._owner(index, shard)
        if owner == self.node_id:
            return self._h_write_shard(payload)
        return self.transport.send_request(owner, A_WRITE_SHARD, payload,
                                           timeout=10.0)

    def get_doc(self, index: str, doc_id: str,
                routing: Optional[str] = None) -> Optional[dict]:
        shard = self._shard_for(index, doc_id, routing)
        entry = self._entry(index, shard)
        payload = {"index": index, "shard": shard, "id": str(doc_id)}
        # prefer the local copy (replica realtime GET reads the op buffer,
        # the adaptive-replica-selection degenerate case) — but only an
        # IN-SYNC one: a replica still in peer recovery is empty
        if (self.node_id in copies_of(entry)
                and self.node_id in (entry.get("in_sync") or [])):
            resp = self._h_get_doc(payload)
        else:
            resp = self.transport.send_request(
                self._owner(index, shard), A_GET_DOC, payload, timeout=10.0)
        return resp.get("doc")

    def _h_write_shard(self, payload: dict) -> dict:
        """Primary write: execute locally, then fan the op out to every
        assigned replica and wait — an in-sync replica that fails is
        reported to the master, which drops it from the group
        (ReplicationOperation.execute:139 / performOnReplicas:221).

        Replication safety: the op is stamped with the routing entry's
        primary term captured BEFORE executing; a replica that fences it
        (its entry moved to a higher term) means this node was deposed —
        it stops acking, self-reports via A_FAIL_COPY, and surfaces a
        retryable 503, never a false ack.  Before the ack the entry is
        re-read: the node must still hold the primary slot at the same
        term (the reference's isPrimaryMode / primary-term re-check)."""
        from opensearch_tpu.common.telemetry import metrics

        index, shard = payload["index"], payload["shard"]
        svc = self.indices.get(index)
        if svc is None:
            raise ShardNotFoundError(
                f"[{index}][{shard}] not on this node")
        engine = svc.engine_for(shard)
        entry = self._entry(index, shard)
        term = int(entry.get("primary_term", 1))
        if entry.get("primary") != self.node_id:
            # misrouted (or raced a failover): refuse before touching the
            # engine — a non-primary executing a write is the split-brain
            # seed the whole fencing layer exists to prevent
            metrics().counter("replication.fenced_ops").inc()
            raise PrimaryFencedError(
                f"[{index}][{shard}] node [{self.node_id}] does not hold "
                f"the primary slot at term [{term}] — retry routes to "
                "the current primary")
        if payload["op"] == "index":
            import json as _json
            n_bytes = len(_json.dumps(payload["source"],
                                      separators=(",", ":")))
            with self.indexing_pressure.coordinating((index, shard),
                                                     n_bytes):
                r = engine.index(payload["id"], payload["source"],
                                 routing=payload.get("routing"))
        else:
            r = engine.delete(payload["id"])
        engine.ensure_synced()
        replicas = list(entry.get("replicas") or [])
        in_sync = set(entry.get("in_sync") or [])
        if replicas:
            rep_op = {"op": payload["op"], "id": r.doc_id,
                      "source": payload.get("source"),
                      "routing": payload.get("routing"),
                      "seq_no": r.seq_no, "version": r.version,
                      "primary_term": term,
                      # the primary's global checkpoint rides every
                      # replication op (ReplicationOperation piggyback)
                      "global_checkpoint": engine.global_checkpoint}
            rep_payload = {"index": index, "shard": shard, "rep_op": rep_op}
            futures = [(rep, self.transport.submit_request(
                rep, A_REPLICATE_OP, rep_payload)) for rep in replicas]
            for rep, fut in futures:
                try:
                    try:
                        resp = fut.result(timeout=10.0)
                    except (NodeDisconnectedError, ReceiveTimeoutError,
                            FuturesTimeout):
                        # transient blip: re-send with bounded backoff
                        # before evicting the copy — replica ops are
                        # seq-no idempotent, so a duplicate of a frame
                        # that DID land is harmless
                        resp = retry_call(
                            "replication",
                            lambda rep=rep: self.transport.send_request(
                                rep, A_REPLICATE_OP, rep_payload,
                                timeout=10.0),
                            max_attempts=2, base_delay=0.05,
                            max_delay=0.5, budget_s=15.0,
                            seed=zlib.crc32(rep.encode()))
                    # the ack advances the replica's retention lease —
                    # translog history stays bounded by the slowest
                    # replica's checkpoint (RetentionLease renewal) —
                    # and its reported local checkpoint feeds the
                    # global-checkpoint computation below
                    engine.add_retention_lease(rep, r.seq_no)
                    lc = (resp.get("local_checkpoint")
                          if isinstance(resp, dict) else None)
                    self._track_replica_ckpt(
                        index, shard, rep,
                        lc if lc is not None else r.seq_no)
                except Exception as exc:
                    if getattr(exc, "remote_type", None) in (
                            "version_conflict_engine_exception",
                            "primary_fenced_exception"):
                        # the replica fenced US for a stale primary term:
                        # the replica is ahead, not broken.  Failing it
                        # would evict an up-to-date copy; instead THIS
                        # node is the deposed one — stop acking, report
                        # ourselves failed so the master promotes a safe
                        # copy if it hasn't already, and refuse with a
                        # retryable 503 so the client re-routes to the
                        # new primary (ReplicationOperation fails the
                        # primary itself on fencing rejections).
                        self._on_primary_fenced(
                            index, shard, term,
                            f"fenced by replica [{rep}] while "
                            f"replicating seq [{r.seq_no}]")
                        raise PrimaryFencedError(
                            f"[{index}][{shard}] primary term [{term}] "
                            f"was fenced by replica [{rep}] — this node "
                            "no longer holds the primary slot; write "
                            "not acknowledged") from exc
                    if rep in in_sync:
                        # the copy must leave the in-sync set BEFORE we ack,
                        # or a later promotion could elect a copy missing
                        # this acked op; if the master is unreachable the
                        # write fails rather than acking unsafely
                        # (ReplicationOperation's fail-shard-then-respond)
                        if not self._report_failed_copy(index, shard, rep):
                            raise NodeDisconnectedError(
                                f"replica [{rep}] failed and the failure "
                                "could not be reported to the cluster "
                                "manager — write not acknowledged")
                    # non-in-sync copies are still recovering: best effort
        # advance the global checkpoint: min over the in-sync copies'
        # local checkpoints (ReplicationTracker.computeGlobalCheckpoint)
        self._update_global_ckpt(index, shard, in_sync, engine)
        # pre-ack re-validation: this node must STILL hold the primary
        # slot at the term the op executed under — a concurrent failover
        # (eviction + promotion elsewhere) means the op may never reach
        # the new lineage, so acking it would be a durability lie
        try:
            cur = self._entry(index, shard)
        except OpenSearchTpuError:
            cur = None
        if cur is None or cur.get("primary") != self.node_id \
                or int(cur.get("primary_term", 1)) != term:
            self._on_primary_fenced(
                index, shard, term,
                "primary slot re-validation failed before ack: entry is "
                f"now [{(cur or {}).get('primary')}] at term "
                f"[{(cur or {}).get('primary_term')}]")
            raise PrimaryFencedError(
                f"[{index}][{shard}] lost the primary slot at term "
                f"[{term}] before the ack — write not acknowledged")
        return {"_index": index, "_id": r.doc_id,
                "_version": r.version, "_seq_no": r.seq_no,
                # the ROUTING entry's term, not a hardcoded 1: fencing
                # (promotions bump it) is observable to clients
                "_primary_term": term,
                "result": r.result, "_shard": shard}

    def _on_primary_fenced(self, index: str, shard: int, term: int,
                           why: str):
        """Common exit for every stop-acking path: count, capture, and
        self-report deposed (best effort — if no master is reachable the
        refused ack already keeps clients safe)."""
        from opensearch_tpu.common.telemetry import flight_recorder, metrics

        metrics().counter("replication.fenced_ops").inc()
        flight_recorder().record(
            "primary_fenced",
            f"[{index}][{shard}] primary [{self.node_id}] at term "
            f"[{term}] stopped acking: {why}",
            detail={"index": index, "shard": shard,
                    "node": self.node_id, "term": term, "why": why})
        self._report_failed_copy(index, shard, self.node_id,
                                 deposed=True)

    def _track_replica_ckpt(self, index: str, shard: int, node: str,
                            ckpt: int):
        with self._lock:
            m = self._local_ckpts.setdefault((index, shard), {})
            m[node] = max(int(ckpt), m.get(node, -1))

    def _update_global_ckpt(self, index: str, shard: int, in_sync: set,
                            engine) -> None:
        """Global checkpoint = min local checkpoint over the in-sync set
        (this primary included).  An in-sync copy we have no report from
        yet pins the computation at -1 — conservative, never unsafe."""
        with self._lock:
            tracked = dict(self._local_ckpts.get((index, shard), {}))
        vals = [engine.local_checkpoint]
        vals += [tracked.get(n, -1) for n in in_sync if n != self.node_id]
        engine.update_global_checkpoint(min(vals))

    def replication_stats(self) -> dict:
        """The replication-safety observability block (``_nodes/stats``
        ``replication``): per-local-shard term/checkpoint positions, the
        primary's tracked per-copy local checkpoints, and the
        replication.* counter family."""
        from opensearch_tpu.common.telemetry import metrics

        m = metrics()
        shards = []
        try:
            state = self.coordinator.state()
        except Exception:  # noqa: BLE001 — stats must not throw pre-join
            state = None
        for name, svc in sorted(self.indices.items()):
            for sid, engine in sorted(svc.local_shards.items()):
                role = "unassigned"
                term = None
                if state is not None:
                    try:
                        e = state.routing[name][sid]
                        term = int(e.get("primary_term", 1))
                        if e.get("primary") == self.node_id:
                            role = "primary"
                        elif self.node_id in (e.get("replicas") or []):
                            role = "replica"
                        elif self.node_id in (e.get("search_replicas")
                                              or []):
                            role = "search"
                    except (KeyError, IndexError):
                        pass
                shards.append({
                    "index": name, "shard": sid, "role": role,
                    "routing_primary_term": term,
                    "engine_primary_term": engine.primary_term,
                    "max_seq_no": engine._seq_no,
                    "local_checkpoint": engine.local_checkpoint,
                    "global_checkpoint": engine.global_checkpoint,
                })
        with self._lock:
            tracked = {f"{k[0]}/{k[1]}": dict(v)
                       for k, v in sorted(self._local_ckpts.items())}
        return {
            "shards": shards,
            "tracked_local_checkpoints": tracked,
            # metric-name-ok: bounded replication counter family
            "counters": {name: m.counter(f"replication.{name}").value
                         for name in ("fenced_ops",
                                      "stale_primary_rejections",
                                      "rollbacks", "resyncs",
                                      "resync_failures",
                                      "durability_checked_ops")},
        }

    def _report_failed_copy(self, index: str, shard: int,
                            node: str, corrupted: bool = False,
                            deposed: bool = False) -> bool:
        try:
            master = self._master()
            payload = {"index": index, "shard": shard, "node": node,
                       "corrupted": corrupted, "deposed": deposed}
            if master == self.node_id:
                self._h_fail_copy(payload)
            else:
                self.transport.send_request(master, A_FAIL_COPY, payload,
                                            timeout=10.0)
            return True
        except OpenSearchTpuError:
            return False   # master unreachable

    # -- corruption-driven copy failover (Store.verify / CorruptedFile) ----

    def verify_local_stores(self, index: Optional[str] = None) -> list:
        """Checksum every local shard copy's on-disk files against its
        commit manifests (``Store.verify``).  A copy that fails runs the
        corruption failover: marker written (by the engine), copy
        reported via ``A_FAIL_COPY``, local data dropped, recovery from
        the primary re-triggered by the resulting cluster state."""
        reports = []
        for name, svc in list(self.indices.items()):
            if index is not None and name != index:
                continue
            for shard_id, engine in sorted(list(
                    svc.local_shards.items())):
                try:
                    engine.verify_store()
                except CorruptIndexError as exc:
                    reports.append({"index": name, "shard": shard_id,
                                    "corrupted": True, "reason": str(exc)})
                    self._on_corruption(name, shard_id, exc)
                except OpenSearchTpuError:
                    continue   # closed mid-iteration
                else:
                    reports.append({"index": name, "shard": shard_id,
                                    "corrupted": False})
        return reports

    def _on_corruption(self, index: str, shard: int,
                       exc: CorruptIndexError):
        """One copy's corruption verdict → the cluster-level response.
        Replica: report itself failed, drop the local copy, let the
        published state re-run peer recovery from the primary.  Primary:
        fail the shard so the master promotes an in-sync replica under a
        bumped term.  Either way the local data is only discarded AFTER
        the master acknowledged the failure — if no master is reachable
        the marker stays and the copy keeps refusing reads rather than
        destroying the only evidence."""
        from opensearch_tpu.common.telemetry import metrics

        key = (index, shard)
        with self._lock:
            if key in self._corrupt_handling:
                return
            self._corrupt_handling.add(key)
        try:
            self._handle_corruption(index, shard, exc, metrics)
        finally:
            with self._lock:
                self._corrupt_handling.discard(key)

    def _handle_corruption(self, index: str, shard: int,
                           exc: CorruptIndexError, metrics):
        metrics().counter("store.corruptions").inc()
        role = self._roles.get((index, shard))
        if role is None:
            return
        if role == "search":
            # a corrupt search-only copy never runs the A_FAIL_COPY
            # protocol (it holds no write state the master must fence):
            # drop the local files and refill from the remote store
            svc = self.indices.get(index)
            if svc is None:
                return
            svc.reset_local_shard(shard)
            with self._lock:
                self._recovered.discard((index, shard))
                if (index, shard) in self._recovering:
                    return
                self._recovering.add((index, shard))
            threading.Thread(
                target=self._run_searcher_recovery, args=(index, shard),
                daemon=True,
                name=f"re-refill-{self.node_id}-{index}-{shard}").start()
            return
        if not self._report_failed_copy(index, shard, self.node_id,
                                        corrupted=True):
            return   # no master: keep the marker, stay read-refusing
        svc = self.indices.get(index)
        if svc is None:
            return
        if role == "primary":
            # promotion happened (or the group went red); whether this
            # node still holds a copy is the NEW state's call — dropping
            # the corrupt files happens when that state assigns us a
            # fresh replica slot (reset below) or removes the shard
            state = self.coordinator.state()
            entry = (state.routing.get(index) or [None] * (shard + 1))[shard]
            if entry is not None and entry.get("primary") == self.node_id:
                return   # no safe copy existed: red, data retained
        svc.reset_local_shard(shard)
        with self._lock:
            self._recovered.discard((index, shard))
        # nudge recovery immediately when the (already-published) state
        # still lists us as a replica copy — otherwise the next applied
        # state triggers it
        try:
            entry = self._entry(index, shard)
        except OpenSearchTpuError:
            return
        if (self.node_id in (entry.get("replicas") or [])
                and entry.get("primary")
                and entry["primary"] != self.node_id):
            with self._lock:
                if (index, shard) in self._recovering:
                    return
                self._recovering.add((index, shard))
            threading.Thread(
                target=self._run_recovery,
                args=(index, shard, entry["primary"]), daemon=True,
                name=f"re-recovery-{self.node_id}-{index}-{shard}").start()

    def _h_replicate_op(self, payload: dict) -> dict:
        """Replica write: FENCE FIRST — an op stamped below the routing
        entry's current primary term comes from a deposed primary that
        doesn't know it yet (split brain); applying it would diverge
        this copy from the lineage the new primary is building.  The
        routing entry hears about promotions before the engine does
        (the engine's own term only advances with applied ops), so the
        fence floor is the max of both views (ReplicationTracker term
        fencing / IndexShard.applyIndexOperationOnReplica).  An apply
        failure propagates to the primary — which fails this copy out of
        in-sync BEFORE the client ack — never into a silent local skip."""
        index, shard = payload["index"], payload["shard"]
        svc = self.indices.get(index)
        if svc is None:
            raise ShardNotFoundError(
                f"[{index}][{shard}] not on this node")
        engine = svc.engine_for(shard)
        rep_op = payload["rep_op"]
        op_term = int(rep_op.get("primary_term", 1))
        floor = self._fence_floor(index, shard, engine)
        if op_term < floor:
            self._record_stale_primary(index, shard, op_term, floor,
                                       rep_op.get("id"))
            raise VersionConflictError(
                str(rep_op.get("id")), f"primary term >= {floor}",
                f"stale primary term {op_term}")
        engine.apply_replica_op(rep_op)
        engine.ensure_synced()
        # the reported local checkpoint feeds the primary's global-
        # checkpoint computation (ReplicationResponse piggyback)
        return {"acknowledged": True,
                "local_checkpoint": engine.local_checkpoint}

    def _fence_floor(self, index: str, shard: int, engine) -> int:
        """The minimum primary term this copy accepts ops under: the
        routing entry's term when cluster state is available (it knows
        about promotions the engine hasn't seen an op under yet), the
        engine's own term always."""
        floor = int(engine.primary_term)
        try:
            entry = self._entry(index, shard)
        except OpenSearchTpuError:
            return floor   # no routing yet (recovery races)
        return max(floor, int(entry.get("primary_term", 1)))

    def _record_stale_primary(self, index: str, shard: int, op_term: int,
                              floor: int, doc_id):
        from opensearch_tpu.common.telemetry import flight_recorder, metrics

        metrics().counter("replication.stale_primary_rejections").inc()
        flight_recorder().record(
            "stale_primary_fenced",
            f"[{index}][{shard}] fenced op at term [{op_term}] below "
            f"current term [{floor}] on [{self.node_id}]",
            detail={"index": index, "shard": shard, "node": self.node_id,
                    "op_term": op_term, "current_term": floor,
                    "doc_id": str(doc_id)})

    def _h_resync(self, payload: dict) -> dict:
        """Replica side of the promotion resync (PrimaryReplicaSyncer /
        TransportResyncReplicationAction): validate the NEW primary's
        term against the routing entry — a stale 'primary' cannot roll
        anyone back — then drop local ops above the old global
        checkpoint and apply the promoted lineage's retained ops (which
        keep their ORIGINAL terms, like the reference's translog-sourced
        resync)."""
        index, shard = payload["index"], payload["shard"]
        svc = self.indices.get(index)
        if svc is None:
            raise ShardNotFoundError(
                f"[{index}][{shard}] not on this node")
        engine = svc.engine_for(shard)
        term = int(payload.get("primary_term", 1))
        floor = self._fence_floor(index, shard, engine)
        if term < floor:
            self._record_stale_primary(index, shard, term, floor,
                                       "<resync>")
            raise VersionConflictError(
                "<resync>", f"primary term >= {floor}",
                f"stale primary term {term}")
        rolled = engine.rollback_above(int(payload.get("above", -1)))
        if rolled:
            from opensearch_tpu.common.telemetry import metrics
            metrics().counter("replication.rollbacks").inc()
        for op in payload.get("ops") or []:
            # ops keep their original terms: the engine's term may
            # already be past them (the promotion bumped it), so the
            # per-op fence is waived — the RESYNC term was validated
            engine.apply_replica_op(op, fence=False)
        engine.advance_primary_term(term)
        engine.ensure_synced()
        return {"acknowledged": True, "rolled_back": rolled,
                "local_checkpoint": engine.local_checkpoint}

    def _run_primary_resync(self, index: str, shard: int, term: int):
        """New-primary side: after promotion, bring every in-sync peer
        onto this copy's lineage — peers roll back above the old global
        checkpoint and replay our retained ops above it.  Best effort
        per peer: an unreachable one is the fault detector's problem
        (it leaves in-sync and re-recovers under the new term)."""
        from opensearch_tpu.common.telemetry import flight_recorder, metrics

        try:
            svc = self.indices.get(index)
            if svc is None:
                return
            engine = svc.engine_for(shard)
            gckpt = int(engine.global_checkpoint)
            ops = engine.ops_since(gckpt)
            entry = self._entry(index, shard)
        except OpenSearchTpuError:
            return
        if ops is None:
            # no contiguous history above the checkpoint: rolling peers
            # back without the ops to replay could CANCEL acked writes —
            # leave them; file-copy recovery re-bootstraps stragglers
            flight_recorder().record(
                "resync_skipped",
                f"[{index}][{shard}] promotion resync skipped: no "
                f"contiguous op history above checkpoint [{gckpt}]",
                detail={"index": index, "shard": shard, "above": gckpt})
            return
        targets = [n for n in (entry.get("replicas") or [])
                   if n in (entry.get("in_sync") or [])
                   and n != self.node_id]
        payload = {"index": index, "shard": shard,
                   "primary_term": int(term), "above": gckpt, "ops": ops}
        for rep in targets:
            try:
                resp = self.transport.send_request(
                    rep, A_RESYNC, payload, timeout=self.recovery_timeout)
                metrics().counter("replication.resyncs").inc()
                lc = resp.get("local_checkpoint") \
                    if isinstance(resp, dict) else None
                if lc is not None:
                    self._track_replica_ckpt(index, shard, rep, lc)
            except OpenSearchTpuError:
                metrics().counter("replication.resync_failures").inc()
                flight_recorder().record(
                    "resync_failed",
                    f"[{index}][{shard}] promotion resync to [{rep}] "
                    "failed",
                    detail={"index": index, "shard": shard,
                            "target": rep, "above": gckpt})

    def _h_get_doc(self, payload: dict) -> dict:
        svc = self.indices.get(payload["index"])
        if svc is None:
            raise ShardNotFoundError(
                f"[{payload['index']}][{payload['shard']}] not on this node")
        doc = svc.engine_for(payload["shard"]).get(payload["id"])
        return {"doc": doc}

    # -- refresh -----------------------------------------------------------

    def refresh(self, index: str):
        state = self.coordinator.state()
        if index not in state.indices:
            raise IndexNotFoundError(index)
        nodes = {e["primary"] for e in state.routing.get(index, [])
                 if e.get("primary")}
        for node in sorted(nodes):
            payload = {"index": index}
            if node == self.node_id:
                self._h_refresh(payload)
            else:
                self.transport.send_request(node, A_REFRESH, payload,
                                            timeout=30.0)

    def _h_refresh(self, payload: dict) -> dict:
        """Refresh local primaries, then publish the new segment-set
        checkpoint to each replica (segrep: the refresh IS the
        replication trigger, ref RemoteStoreRefreshListener/
        SegmentReplicationTargetService.onNewCheckpoint:208)."""
        index = payload["index"]
        svc = self.indices.get(index)
        if svc is None:
            return {"ok": True}
        svc.refresh()
        for shard in list(svc.local_shards):
            if self._roles.get((index, shard)) != "primary":
                continue
            try:
                entry = self._entry(index, shard)
            except OpenSearchTpuError:
                continue
            replicas = entry.get("replicas") or []
            if replicas:
                ckpt = svc.engine_for(shard).checkpoint_info()
                payload2 = {"index": index, "shard": shard, "ckpt": ckpt}
                futures = [self.transport.submit_request(
                    rep, A_PUBLISH_CKPT, payload2) for rep in replicas]
                for fut in futures:
                    try:
                        fut.result(timeout=self.recovery_timeout)
                    except Exception:
                        pass  # replica catches up on the next checkpoint
            searchers = entry.get("search_replicas") or []
            if searchers and self.remote_store is not None:
                self._publish_search_checkpoint(svc, index, shard,
                                                searchers)
        return {"ok": True}

    def _publish_search_checkpoint(self, svc, index: str, shard: int,
                                   searchers: list) -> None:
        """Primary side of search-tier segment replication: commit the
        shard, upload its segment files content-addressed into the
        remote store (PR-8 manifests; the snapshot blob dedup space, so
        unchanged segments upload nothing), then publish a checkpoint
        NAMING the remote blob digests to every search replica.  The
        searchers pull from the store — this RPC carries metadata only,
        and a failed/unreachable searcher just lags (bounded by
        ``search.replication.max_lag``) until the next publish or its
        own refill."""
        from opensearch_tpu.common.telemetry import metrics
        from opensearch_tpu.index.remote_store import upload_shard
        engine = svc.engine_for(shard)
        try:
            commit = engine.flush()
            info = upload_shard(
                self.remote_store, index, shard, engine, commit,
                extra={"primary_term": engine.primary_term})
        except (OpenSearchTpuError, OSError) as e:
            # the remote store being down must never fail the refresh:
            # searchers serve their last installed checkpoint and catch
            # up when the store returns
            import logging
            logging.getLogger("opensearch_tpu.remote_store").warning(
                "[%s][%s] search-checkpoint upload failed: %s",
                index, shard, e)
            metrics().counter("segrep.publish_failures").inc()
            return
        metrics().counter("segrep.publishes").inc()
        ckpt = engine.checkpoint_info()
        committed = set(commit["segments"])
        # publish exactly the uploaded commit: a concurrent refresh may
        # already have grown engine state past what the store holds
        ckpt["segments"] = [sid for sid in ckpt["segments"]
                            if sid in committed]
        ckpt["max_seq_no"] = commit["max_seq_no"]
        files: dict[str, list] = {}
        for fmeta in info["file_metas"]:
            for suffix in (".npz", ".json", ".src", ".liv"):
                if fmeta["name"].endswith(suffix):
                    files.setdefault(
                        fmeta["name"][:-len(suffix)], []).append(fmeta)
                    break
        payload = {"index": index, "shard": shard, "ckpt": ckpt,
                   "files": files}
        futures = [self.transport.submit_request(
            node, A_PUBLISH_SEARCH_CKPT, payload) for node in searchers]
        for fut in futures:
            try:
                fut.result(timeout=self.recovery_timeout)
            except Exception:
                pass   # the searcher lags; bounded by max_lag deranking

    def _h_publish_ckpt(self, payload: dict) -> dict:
        """Replica: diff the checkpoint against local segments, pull the
        missing ones from the primary, install."""
        index, shard, ckpt = payload["index"], payload["shard"], payload["ckpt"]
        svc = self.indices.get(index)
        if svc is None:
            raise ShardNotFoundError(f"[{index}][{shard}] not on this node")
        engine = svc.engine_for(shard)
        have = {s.seg_id for s in engine.segments}
        missing = [sid for sid in ckpt["segments"] if sid not in have]
        blobs = {}
        if missing:
            primary = self._entry(index, shard).get("primary")
            # transient drops/timeouts retry with bounded backoff under
            # the configurable recovery budget instead of one bare
            # 30s-hardcoded RPC; attempts/retries/exhaustions land in
            # the retry.recovery.fetch.* counters (_nodes/stats
            # `recovery`)
            resp = retry_call(
                "recovery.fetch",
                lambda: self.transport.send_request(
                    primary, A_FETCH_SEGMENTS,
                    {"index": index, "shard": shard,
                     "seg_ids": missing},
                    timeout=self.recovery_timeout),
                max_attempts=3, base_delay=0.05, max_delay=0.5,
                budget_s=3.0 * self.recovery_timeout,
                seed=zlib.crc32(
                    f"{self.node_id}/{index}/{shard}/fetch".encode()))
            blobs = resp["blobs"]
        try:
            engine.install_checkpoint(ckpt, blobs)
        except CorruptIndexError:
            # damaged in flight: refuse the install (nothing mutated) and
            # catch up on the next published checkpoint or peer recovery
            from opensearch_tpu.common.telemetry import metrics
            metrics().counter("recovery.corrupt_blobs").inc()
            raise
        svc.invalidate_searcher()
        return {"acknowledged": True}

    def _h_fetch_segments(self, payload: dict) -> dict:
        svc = self.indices.get(payload["index"])
        if svc is None:
            raise ShardNotFoundError(
                f"[{payload['index']}][{payload['shard']}] not on this node")
        engine = svc.engine_for(payload["shard"])
        return {"blobs": engine.segments_blobs(payload["seg_ids"])}

    # -- search-replica tier (segrep over the remote store) ----------------

    def _fetch_blob_verified(self, fmeta: dict) -> bytes:
        """Pull one content-addressed blob through the node FileCache
        and verify its CRC against the checkpoint manifest BEFORE any
        byte reaches an installable file.  A corrupt blob is dropped
        from the cache and re-fetched once (counted); a second failure
        raises so the caller can mark the segment."""
        from opensearch_tpu.common.telemetry import metrics

        blob = fmeta["blob"]
        want_crc = fmeta.get("crc32")

        def fetch() -> bytes:
            data = self.remote_store.blobs.read_blob(blob)
            m = metrics()
            m.counter("segrep.fetches").inc()
            m.counter("segrep.bytes_pulled").inc(len(data))
            return data

        for attempt in range(2):
            path = self.file_cache.get(blob, fetch)
            with open(path, "rb") as f:
                data = f.read()
            if want_crc is None or \
                    (zlib.crc32(data) & 0xFFFFFFFF) == int(want_crc):
                return data
            metrics().counter("segrep.corrupt_blobs").inc()
            self.file_cache.invalidate(blob)
        # the cache holds nothing for this digest now: a repaired
        # repository heals on the next fetch
        raise CorruptIndexError(
            f"remote blob [{blob}] for [{fmeta['name']}] failed CRC "
            "verification after re-fetch")

    def _fetch_remote_segment(self, engine, seg_id: str,
                              fmetas: list):
        """Materialize one segment from the remote store into the local
        shard directory: every file pulled via the FileCache (stable
        cache paths, symlinked like a searchable-snapshot mount, so an
        evicted blob heals by re-fetch) and CRC-verified; the PR-8
        commit manifest is regenerated from the verified bytes so the
        store stays checksum-verifiable.  Repeated corruption writes a
        marker naming the segment (``corrupted_<seg>.json``)."""
        from opensearch_tpu.index.store import (file_checksum,
                                                load_segment,
                                                write_corruption_marker,
                                                write_segment_manifest)
        from opensearch_tpu.index.remote_store import \
            validate_manifest_name

        seg_dir = os.path.join(engine.data_path, "segments")
        os.makedirs(seg_dir, exist_ok=True)
        entries = {}
        # the whole file set stays pinned until the segment is LOADED:
        # fetching file N must not evict file 1 before the bytes are
        # staged (materialize_shard's pin discipline)
        with self.file_cache.pin({f["blob"] for f in fmetas}):
            try:
                for fmeta in sorted(fmetas, key=lambda f: f["name"]):
                    name = fmeta["name"]
                    validate_manifest_name(name)
                    data = self._fetch_blob_verified(fmeta)
                    link = os.path.join(seg_dir, name)
                    if os.path.islink(link) or os.path.exists(link):
                        os.remove(link)
                    os.symlink(self.file_cache.path(fmeta["blob"]),
                               link)
                    if not name.endswith(".liv"):
                        entries[name] = file_checksum(data)
            except CorruptIndexError as e:
                # marker on repeat: the refill/ckpt install that hits
                # this again resets the copy instead of trusting the
                # store
                write_corruption_marker(seg_dir, seg_id, str(e))
                raise
            write_segment_manifest(seg_dir, seg_id, entries)
            return load_segment(seg_dir, seg_id)

    def _h_publish_search_ckpt(self, payload: dict) -> dict:
        """Search replica: install a primary-published checkpoint by
        pulling the named blob digests from the remote store — the
        primary is NEVER contacted.  A failed install leaves the
        recorded published seq ahead of the installed one: that gap IS
        the replication lag the C3 selector bounds."""
        from opensearch_tpu.common.telemetry import metrics

        index, shard = payload["index"], payload["shard"]
        ckpt, files = payload["ckpt"], payload.get("files") or {}
        svc = self.indices.get(index)
        if svc is None:
            raise ShardNotFoundError(f"[{index}][{shard}] not on this node")
        if self.remote_store is None or self.file_cache is None:
            raise OpenSearchTpuError(
                f"node [{self.node_id}] has no remote store / file "
                "cache: cannot install search checkpoints")
        engine = svc.engine_for(shard)
        engine.search_only = True
        key = (index, shard)
        with self._lock:
            self._search_published[key] = max(
                self._search_published.get(key, -1),
                int(ckpt["max_seq_no"]))
        try:
            if engine.corruption is not None:
                # a marked copy re-pulls from scratch (cache refill is
                # the searcher's only recovery path)
                svc.reset_local_shard(shard)
                engine = svc.engine_for(shard)
                engine.search_only = True
            have = {s.seg_id for s in engine.segments}
            segs = {sid: self._fetch_remote_segment(
                        engine, sid, files.get(sid) or [])
                    for sid in ckpt["segments"] if sid not in have}
            engine.install_remote_checkpoint(ckpt, segs)
        except OpenSearchTpuError:
            metrics().counter("segrep.install_failures").inc()
            raise
        svc.invalidate_searcher()
        with self._lock:
            self._search_installed[key] = max(
                self._search_installed.get(key, -1),
                int(ckpt["max_seq_no"]))
        metrics().counter("segrep.installs").inc()
        return {"acknowledged": True, "lag": self.search_lag()}

    def _run_searcher_recovery(self, index: str, shard: int):
        """Bootstrap (or re-bootstrap) a search-only copy purely from
        the remote store: read the shard's manifest, pull every blob
        through the FileCache, install.  Zero primary-directed RPCs —
        the only transport traffic is the readiness report to the
        cluster manager, so a primary failure never stalls searcher
        recovery (the tier-separation point)."""
        from opensearch_tpu.common.telemetry import metrics
        from opensearch_tpu.index.remote_store import read_manifest

        t0 = time.monotonic()
        try:
            svc = self.indices.get(index)
            if svc is None:
                return
            engine = svc.engine_for(shard)
            if engine.corruption is not None:
                svc.reset_local_shard(shard)
                engine = svc.engine_for(shard)
            engine.search_only = True
            manifest = None
            if self.remote_store is not None:
                try:
                    manifest = read_manifest(self.remote_store, index,
                                             shard)
                except (OpenSearchTpuError, OSError, ValueError):
                    # store unreachable: stay un-recovered; the next
                    # applied state (or published checkpoint) retries
                    metrics().counter("segrep.refill_failures").inc()
                    return
            installed_seq = -1
            if manifest is not None:
                files: dict[str, list] = {}
                for fmeta in manifest["files"]:
                    for suffix in (".npz", ".json", ".src", ".liv"):
                        if fmeta["name"].endswith(suffix):
                            files.setdefault(
                                fmeta["name"][:-len(suffix)],
                                []).append(fmeta)
                            break
                commit = manifest["commit"]
                have = {s.seg_id for s in engine.segments}
                try:
                    segs = {sid: self._fetch_remote_segment(
                                engine, sid, files.get(sid) or [])
                            for sid in commit["segments"]
                            if sid not in have}
                except CorruptIndexError:
                    metrics().counter("segrep.refill_failures").inc()
                    return   # marker written; next attempt resets
                installed_seq = int(commit["max_seq_no"])
                engine.install_remote_checkpoint(
                    {"segments": commit["segments"],
                     "max_seq_no": installed_seq,
                     "primary_term": int(manifest.get(
                         "primary_term", 1))}, segs)
                svc.invalidate_searcher()
            key = (index, shard)
            with self._lock:
                self._search_published[key] = max(
                    self._search_published.get(key, -1), installed_seq)
                self._search_installed[key] = max(
                    self._search_installed.get(key, -1), installed_seq)
            master = self._master()
            payload = {"index": index, "shard": shard,
                       "node": self.node_id}
            if master == self.node_id:
                self._h_search_shard_ready(payload)
            else:
                retry_call(
                    "recovery.report",
                    lambda: self.transport.send_request(
                        master, A_SEARCH_SHARD_READY, payload,
                        timeout=10.0),
                    max_attempts=2, base_delay=0.05,
                    seed=zlib.crc32(self.node_id.encode()))
            with self._lock:
                self._recovered.add(key)
            m = metrics()
            m.counter("segrep.refills").inc()
            m.histogram("segrep.refill_ms").observe(
                (time.monotonic() - t0) * 1000.0)
        except OpenSearchTpuError:
            pass   # next cluster-state application retries
        finally:
            with self._lock:
                self._recovering.discard((index, shard))

    def _h_search_shard_ready(self, payload: dict) -> dict:  # actuator-ok (in-sync bookkeeping, not fleet/QoS actuation)
        """Master: a search replica finished its remote-store refill —
        admit it to the shard group's ``search_in_sync`` serving set."""
        index, shard, node = (payload["index"], payload["shard"],
                              payload["node"])

        def update(state: ClusterState) -> ClusterState:
            routing = {k: [dict(e) for e in v]
                       for k, v in state.routing.items()}
            entries = routing.get(index)
            if entries is None or shard >= len(entries):
                return state
            e = entries[shard]
            if node in (e.get("search_replicas") or []) \
                    and node not in (e.get("search_in_sync") or []):
                e["search_in_sync"] = \
                    list(e.get("search_in_sync") or []) + [node]
                return state.with_(routing=routing)
            return state
        self.coordinator.submit_state_update(update)
        return {"acknowledged": True}

    def search_lag(self) -> int:
        """This searcher's replication lag: max over local search-only
        shards of (last published checkpoint seq seen) - (last
        installed seq) — 0 when fully caught up.  Piggybacked on every
        search response and fault-detection ping (``node_load``)."""
        with self._lock:
            return max(
                (max(0, p - self._search_installed.get(k, -1))
                 for k, p in self._search_published.items()),
                default=0)

    def shard_search_lag(self, index: str, shard: int) -> Optional[int]:
        key = (index, shard)
        with self._lock:
            if key not in self._search_published:
                return None
            return max(0, self._search_published[key]
                       - self._search_installed.get(key, -1))

    def search_installed_seq(self, index: str, shard: int) -> int:
        """Highest checkpoint seq this searcher has installed for the
        shard (-1 = nothing installed) — the harness's catch-up probe."""
        with self._lock:
            return self._search_installed.get((index, shard), -1)

    def search_tier_stats(self) -> dict:
        """The searcher-tier observability block (``_nodes/stats``-
        style): role, per-shard lag, FileCache pressure, and the
        segrep.* counter family."""
        from opensearch_tpu.common.telemetry import metrics

        m = metrics()
        with self._lock:
            lags = {f"{k[0]}/{k[1]}":
                    max(0, p - self._search_installed.get(k, -1))
                    for k, p in sorted(self._search_published.items())}
        return {
            "roles": list(self.roles),
            "max_lag": max(lags.values(), default=0),
            "shard_lag": lags,
            "file_cache": (self.file_cache.stats()
                           if self.file_cache is not None else None),
            # metric-name-ok: bounded segrep counter family
            "segrep": {name: m.counter(f"segrep.{name}").value
                       for name in ("publishes", "publish_failures",
                                    "installs", "install_failures",
                                    "fetches", "bytes_pulled",
                                    "corrupt_blobs", "refills",
                                    "refill_failures")},
            "autoscale": self.autoscaler.stats(),
        }

    # -- task cancellation propagation -------------------------------------

    def _h_ban_parent(self, payload: dict) -> dict:
        """Ban (or lift the ban on) a parent task id: running children
        are cancelled, late-registering children arrive pre-cancelled
        (ref TaskCancellationService.BanParentTaskRequest)."""
        pid = payload["parent_task_id"]
        if payload.get("ban", True):
            cancelled = self.task_manager.ban_parent(
                pid, payload.get("reason", "parent task was cancelled"))
            return {"cancelled": len(cancelled)}
        self.task_manager.unban_parent(pid)
        return {"cancelled": 0}

    def _broadcast_ban(self, parent_id: str, nodes, reason: str,
                       ban: bool = True) -> None:
        """Fire-and-forget ban/unban to every node that (may) run
        children of ``parent_id``; the local manager is hit directly."""
        payload = {"parent_task_id": parent_id, "reason": reason,
                   "ban": ban}
        for node in nodes:
            try:
                if node == self.node_id:
                    self._h_ban_parent(payload)
                else:
                    self.transport.submit_request(node, A_BAN_PARENT,
                                                  payload)
            except Exception:  # noqa: BLE001 — best effort per node
                pass

    # -- search (scatter-gather) -------------------------------------------

    def _load_stats(self) -> dict:
        """This node's load snapshot, piggybacked on every search[shards]
        response and fault-detection ping — the evidence coordinators
        rank shard copies with (ResponseCollectorService ingestion
        format)."""
        tasks = self.task_manager.list()
        with self._lock:
            service_ewma = self._service_time_ewma.value
        out = {
            "node": self.node_id,
            "duress": self.search_backpressure.in_duress(),
            "fs_healthy": self.fs_health.healthy,
            "queue_size": sum(
                1 for t in tasks
                if t.action.startswith("indices:data/read/search")),
            "active_tasks": len(tasks),
            "service_time_ewma_nanos": int(service_ewma or 0),
        }
        if self.is_search:
            # checkpoint lag rides every ping/response so coordinators
            # can bound searcher staleness (search.replication.max_lag)
            out["search_lag"] = self.search_lag()
        return out

    def _copy_candidates(self, entry: dict, spill: int = 0,
                         prov: "Optional[dict]" = None) -> list[str]:
        """Shard-copy dispatch/failover order.  Legacy order — LOCAL
        in-sync copy first, then the primary, then in-sync replicas —
        is the no-evidence baseline; with response samples recorded the
        C3 rank reorders copies (adaptive replica selection,
        OperationRouting.rankShardsAndUpdateStats), nodes in duress
        derank to the back but stay as copies of last resort, and
        ``spill`` rotates msearch batch members across the healthy
        copies so a burst spreads over replicas.  Copies still in peer
        recovery are excluded — they would silently answer from an empty
        engine (AbstractSearchAsyncAction's ShardIterator).

        ``prov`` (profiled requests only) is filled with the selection
        provenance — legacy order, whether adaptive selection rerouted
        the preferred copy, and the spill rotation — so the Profile API
        can report WHY a copy was chosen."""
        from opensearch_tpu.cluster import response_collector as rc
        from opensearch_tpu.common.telemetry import metrics

        in_sync = set(entry.get("in_sync") or [])
        order = [n for n in copies_of(entry) if n in in_sync]
        if not order and entry.get("primary"):
            # transitional states (stale promotion mid-flight) may leave
            # an empty in-sync set; the primary is still the best copy
            order = [entry["primary"]]
        if self.node_id in order:
            order.remove(self.node_id)
            order.insert(0, self.node_id)
        # search-replica tier: READY searchers lead the baseline order
        # — even over a local write copy, the way the reference's
        # search-role routing strictly prefers the serving tier (taking
        # reads off the write path is the tier's point) — unless the
        # coordinator has recorded them past the checkpoint-lag bound,
        # in which case they fall to copy-of-last-resort like a duress
        # node.  Write copies stay in the list, so the search tier
        # failing wholesale degrades to the legacy read path instead of
        # failing the shard.
        searchers = search_copies_of(entry)
        if searchers:
            collector = self.response_collector
            fresh = [n for n in searchers if not collector.lagging(n)]
            stale = [n for n in searchers if collector.lagging(n)]
            if self.node_id in fresh:      # local searcher copy first
                fresh.remove(self.node_id)
                fresh.insert(0, self.node_id)
            order = (fresh + [n for n in order if n not in fresh]
                     + [n for n in stale if n not in order])
        if prov is not None:
            prov["legacy_order"] = list(order)
            prov["spill"] = int(spill)
        if not rc.ADAPTIVE_ENABLED or len(order) < 2:
            if prov is not None:
                prov["rerouted"] = False
            return order
        collector = self.response_collector
        ranked, rerouted = collector.rank_copies(order)
        if rerouted:
            metrics().counter("search.replica_selection.reroutes").inc()
        if prov is not None:
            prov["rerouted"] = bool(rerouted)
        if spill:
            # round-robin the healthy prefix: msearch batch member i
            # starts at healthy copy i % n (replica spill)
            healthy = [n for n in ranked
                       if not collector.in_duress(n)
                       and not collector.lagging(n)]
            if len(healthy) > 1:
                k = spill % len(healthy)
                ranked = (healthy[k:] + healthy[:k]
                          + [n for n in ranked if n not in healthy])
        elif rc.SPILL_OUTSTANDING > 0:
            # single-search spill: the C3 rank only moves once response
            # samples land, but outstanding counts move per RPC — a
            # burst of plain _search requests rotates off the preferred
            # copy the moment it has too many in flight, instead of
            # queueing behind the EWMA's reaction time
            pref = ranked[0]
            if collector.outstanding(pref) > rc.SPILL_OUTSTANDING:
                alts = [n for n in ranked[1:]
                        if not collector.in_duress(n)
                        and not collector.lagging(n)]
                if alts:
                    alt = min(alts, key=collector.outstanding)
                    if collector.outstanding(alt) \
                            < collector.outstanding(pref):
                        ranked.remove(alt)
                        ranked.insert(0, alt)
                        metrics().counter(
                            "search.replica_selection.reroutes").inc()
        if prov is not None:
            # spill rotation / outstanding-count spill also count as a
            # changed preference
            prov["rerouted"] = prov["rerouted"] or (
                bool(ranked) and ranked[0] != order[0])
        return ranked

    def _query_group(self, node: str, payload: dict) -> dict:
        """One shard-group query phase RPC (local short-circuit).  The
        measured response time and the piggybacked load snapshot feed
        the response collector; degradable failures penalize the node's
        EWMA so repeated timeouts actually derank the copy."""
        collector = self.response_collector
        collector.incr_outstanding(node)
        start = time.monotonic()
        try:
            if node == self.node_id:
                resp = self._h_search_shards(payload)
            else:
                fut = self.transport.submit_request(node, A_SEARCH_SHARDS,
                                                    payload)
                try:
                    resp = fut.result(timeout=self.search_rpc_timeout)
                except FuturesTimeout:
                    raise ReceiveTimeoutError(
                        f"[{node}][{A_SEARCH_SHARDS}] timed out") from None
        except OpenSearchTpuError as exc:
            if _degradable_search_error(exc):
                collector.record_failure(
                    node, (time.monotonic() - start) * 1e9)
            raise
        finally:
            collector.decr_outstanding(node)
        collector.record_response(node, (time.monotonic() - start) * 1e9,
                                  resp.get("node_load"))
        return resp

    def search(self, index: str, body: Optional[dict] = None, *,
               _spill: int = 0) -> dict:
        """Coordinator side: group shards by their preferred copy's node,
        one RPC per node; a failed node sends its shards to their NEXT
        copy (per-shard failover iterators); shards whose every copy
        failed degrade to ``_shards.failed`` entries when partial
        results are allowed, and the survivors' top-k merges on this
        node.  ``_spill`` is the msearch batch-member index — it rotates
        each shard's healthy copies so a batch spreads over replicas."""
        from opensearch_tpu.search import executor as _exec

        body = dict(body or {})
        allow_partial = body.pop("allow_partial_search_results", None)
        if allow_partial is None:
            allow_partial = _exec.DEFAULT_ALLOW_PARTIAL_RESULTS
        allow_partial = bool(allow_partial)
        # coordinator-scope admission: the scatter holds a permit from
        # the SAME gate the REST edge uses, so cluster searches and HTTP
        # searches share one concurrency budget (and one occupancy
        # signal for the shed decision below); saturation rejects with
        # 429 here instead of queueing scatters unboundedly.  The
        # enclosing task's X-Opaque-Id is the tenant key, so a tenant
        # over its carved share rejects here too (search.qos)
        from opensearch_tpu.common import tasks as taskmod
        outer = taskmod.current()
        tenant = (outer.headers.get("X-Opaque-Id")
                  if outer is not None else None)
        self.qos.maybe_tick()
        # the elasticity loop ticks on the same cadence source as QoS:
        # traffic (no background thread — deterministic under the soak)
        self.autoscaler.maybe_tick()
        with self.search_backpressure.admission.acquire("search",
                                                        tenant=tenant):
            return self._search_admitted(index, body, allow_partial,
                                         _spill)

    def _search_admitted(self, index: str, body: dict,
                         allow_partial: bool, _spill: int) -> dict:
        from opensearch_tpu.cluster import response_collector as rc
        from opensearch_tpu.common import tasks as taskmod
        from opensearch_tpu.common.errors import NodeDuressError
        from opensearch_tpu.common.telemetry import metrics
        from opensearch_tpu.search import executor as _exec

        state = self.coordinator.state()
        routing = state.routing.get(index)
        if routing is None:
            raise IndexNotFoundError(index)
        candidates: dict[int, list[str]] = {}
        failures: list[dict] = []
        # copy-selection provenance, kept ONLY for profiled requests
        # (the Profile API's reroute/spill attribution)
        profile_prov: "Optional[dict]" = \
            {} if body.get("profile") else None
        for shard, entry in enumerate(routing):
            shard_prov = {} if profile_prov is not None else None
            cands = self._copy_candidates(entry, spill=_spill,
                                          prov=shard_prov)
            if profile_prov is not None:
                profile_prov[shard] = shard_prov
            if not cands:
                exc = ShardNotFoundError(f"[{index}][{shard}] unassigned")
                if not allow_partial:
                    raise exc
                failures.append(_exec.shard_failure_entry(
                    index, shard, None, exc))
                continue
            candidates[shard] = cands
        # coordinator-side load shedding: a shard whose EVERY in-sync
        # copy reports duress fails fast into _shards.failures[] instead
        # of queueing onto a collapsing node (only under partial-results
        # semantics — with allow_partial=false the client asked for
        # all-or-nothing, so we still try).  The decision consults the
        # admission gate's occupancy: below the configured fraction the
        # coordinator still has capacity to try a duressed copy as a
        # last resort; at/above it the shed fails fast, and draws from
        # the same rejection budget as the gate's 429s.  The threshold
        # is tenant-weighted: a QoS-penalized (noisy) tenant's requests
        # shed at proportionally lower occupancy, so the aggressor's
        # traffic degrades before the duressed copies see it
        outer = taskmod.current()
        outer_opaque = (outer.headers.get("X-Opaque-Id")
                        if outer is not None else None)
        admission = self.search_backpressure.admission
        shed_threshold = (rc.SHED_OCCUPANCY
                          * admission.shed_priority(outer_opaque))
        if allow_partial and rc.SHED_ON_DURESS \
                and admission.occupancy() >= shed_threshold:
            for shard in sorted(candidates):
                cands = candidates[shard]
                if not all(self.response_collector.in_duress(n)
                           for n in cands):
                    continue
                metrics().counter("search.replica_selection.sheds").inc()
                admission.record_shed(tenant=outer_opaque)
                failures.append(_exec.shard_failure_entry(
                    index, shard, cands[0], NodeDuressError(
                        f"[{index}][{shard}] shed: all in-sync copies "
                        f"{cands} report duress")))
                del candidates[shard]

        aggs_requested = bool(body.get("aggs") or body.get("aggregations"))

        # the coordinator search is itself a registered, cancellable
        # task; its id is the parent id every remote shard task carries,
        # and cancelling it broadcasts a ban to every involved node.
        # Client-attribution headers copy down from the enclosing task
        # (the reference's HEADERS_TO_COPY) so X-Opaque-Id reaches the
        # scatter payloads and this node's insight records
        task = self.task_manager.register(
            "indices:data/read/search", f"search [{index}]",
            headers=({"X-Opaque-Id": outer_opaque}
                     if outer_opaque else None))
        token = taskmod.set_current(task)
        parent_id = f"{self.node_id}:{task.id}"
        involved = sorted({n for cands in candidates.values()
                           for n in cands})
        task.add_cancellation_listener(
            lambda: self._broadcast_ban(
                parent_id, involved,
                f"coordinator task [{parent_id}] was cancelled: "
                f"{task.cancel_reason}"))
        try:
            return self._search_scatter(
                index, body, routing, candidates, failures,
                allow_partial, aggs_requested, task, parent_id,
                profile_prov=profile_prov)
        finally:
            taskmod.reset_current(token)
            self.task_manager.unregister(task)
            if task.cancelled:
                # lift the bans so the parent id doesn't pin a ban slot
                # on nodes that will never see another child of it
                self._broadcast_ban(parent_id, involved, "completed",
                                    ban=False)

    def msearch(self, index: str, bodies: list) -> dict:
        """Batched scatter (_msearch at cluster scope): sub-request i
        passes its batch index as the spill offset, so a same-index
        burst round-robins over each shard's healthy copies instead of
        piling onto the single preferred one (the reference spreads
        load via ARS rank updates per request; with batches arriving
        faster than EWMAs move, explicit rotation is the deterministic
        equivalent).  Errors are per sub-request, like REST _msearch."""
        responses: list = []
        for i, body in enumerate(bodies):
            try:
                responses.append(self.search(index, dict(body or {}),
                                             _spill=i))
            except OpenSearchTpuError as e:
                responses.append({"error": {"type": e.error_type,
                                            "reason": e.reason},
                                  "status": e.status})
        return {"responses": responses}

    def _search_scatter(self, index, body, routing, candidates, failures,
                        allow_partial, aggs_requested, task, parent_id,
                        profile_prov=None):
        from opensearch_tpu.common.tasks import TaskCancelledException
        from opensearch_tpu.common.telemetry import metrics, tracer
        from opensearch_tpu.search import executor as _exec
        from opensearch_tpu.search.executor import merge_hit_rows

        opaque_id = task.headers.get("X-Opaque-Id")
        size = int(body.get("size", 10))
        from_ = int(body.get("from", 0))
        sub = dict(body)
        sub["from"] = 0
        sub["size"] = from_ + size
        profiling = bool(body.get("profile"))
        t_scatter = time.monotonic() if profiling else 0.0

        # coordinator span: the scatter RPCs inject its trace context, so
        # every remote shard query phase parents under this trace
        with tracer().start_span(
                "search.coordinator",
                {"index": index, "node": self.node_id,
                 "shards": len(routing)}):
            responses = []
            resp_meta = []      # parallels responses: (node, shards) —
            # kept always (two small tuples per RPC) so the profile
            # merge below can attribute each section to its copy
            attempt = {shard: 0 for shard in candidates}
            pending = set(candidates)
            while pending:
                if task.cancelled:
                    # cancelled mid-scatter: stop issuing RPCs, count the
                    # un-queried shards as failures and return what we
                    # have (the ban broadcast reaps in-flight children)
                    exc = TaskCancelledException(
                        f"task [{parent_id}] was cancelled: "
                        f"{task.cancel_reason}")
                    for shard in sorted(pending):
                        failures.append(_exec.shard_failure_entry(
                            index, shard, None, exc))
                    pending.clear()
                    break
                by_node: dict[str, list[int]] = {}
                for shard in sorted(pending):
                    node = candidates[shard][attempt[shard]]
                    by_node.setdefault(node, []).append(shard)
                for node, shards in by_node.items():
                    payload = {"index": index, "shards": shards,
                               "body": sub,
                               "agg_partials": aggs_requested,
                               "parent_task_id": parent_id}
                    if opaque_id:
                        # client attribution travels with the shard
                        # query phase so data-node insight records (and
                        # _tasks) name the client, not just the
                        # coordinator
                        payload["opaque_id"] = opaque_id
                    try:
                        responses.append(self._query_group(node, payload))
                        resp_meta.append((node, list(shards)))
                        pending.difference_update(shards)
                        continue
                    except OpenSearchTpuError as exc:
                        if not _degradable_search_error(exc):
                            raise   # client errors (bad query) stay 4xx
                        last = exc
                    # the whole group fails over: each of its shards
                    # advances to its next copy; a shard out of copies
                    # becomes a counted failure
                    for shard in shards:
                        attempt[shard] += 1
                        if attempt[shard] < len(candidates[shard]):
                            metrics().counter(
                                "search.shard_failover").inc()
                            continue
                        pending.discard(shard)
                        metrics().counter("search.shard_failures").inc()
                        failures.append(_exec.shard_failure_entry(
                            index, shard, node, last))
            if failures and not allow_partial:
                from opensearch_tpu.common.errors import \
                    SearchPhaseExecutionError
                raise SearchPhaseExecutionError(
                    "query",
                    f"{len(failures)} of {len(routing)} shards failed "
                    f"and [allow_partial_search_results] is false",
                    failures)

            total = 0
            max_score = None
            rows = []
            for node_idx, resp in enumerate(responses):
                r = resp["resp"]
                for pos, h in enumerate(r["hits"]["hits"]):
                    rows.append((h, node_idx, pos))
                total += r["hits"]["total"]["value"]
                ms = r["hits"]["max_score"]
                if ms is not None and (max_score is None or ms > max_score):
                    max_score = ms
            scatter_s = (time.monotonic() - t_scatter) if profiling \
                else 0.0
            t_reduce = time.monotonic() if profiling else 0.0
            with tracer().start_span("coordinator.reduce",
                                     {"sources": len(responses),
                                      "rows": len(rows)}):
                all_hits = merge_hit_rows(rows, body.get("sort"))
            reduce_s = (time.monotonic() - t_reduce) if profiling \
                else 0.0
        n_shards = len(routing)
        out = {
            "took": max((resp["resp"]["took"] for resp in responses),
                        default=0),
            # one shard running out of budget flags the whole response
            "timed_out": any(resp["resp"].get("timed_out")
                             for resp in responses),
            "_shards": _exec.shards_section(n_shards, failures),
            "hits": {"total": {"value": total, "relation": "eq"},
                     "max_score": max_score,
                     "hits": all_hits[from_: from_ + size]},
        }
        if aggs_requested:
            # coordinator reduce of each node's mergeable partials
            # (InternalAggregations.reduce / QueryPhaseResultConsumer:178)
            from opensearch_tpu.search.aggs import reduce_aggs
            aggs_json = body.get("aggs") or body.get("aggregations")
            out["aggregations"] = reduce_aggs(
                aggs_json,
                [resp["resp"].get("aggregation_partials") or {}
                 for resp in responses])
        if body.get("suggest"):
            from opensearch_tpu.search.suggest import merge_suggest
            out["suggest"] = merge_suggest(
                [resp["resp"].get("suggest") for resp in responses])
        if profiling:
            out["profile"] = self._merge_profiles(
                responses, resp_meta, profile_prov, attempt,
                scatter_s, reduce_s, failures)
        # coordinator-level insight record: the SCATTER is this node's
        # workload evidence (data nodes recorded their own query
        # phases); outcome classification covers the degradations only
        # this layer sees — duress sheds and partial results
        if failures and any(
                (f.get("reason") or {}).get("type")
                == "node_duress_exception" for f in failures):
            outcome = "shed"
        elif failures:
            outcome = "partial"
        elif out["timed_out"]:
            outcome = "timeout"
        else:
            outcome = "ok"
        task.record_checkpoint()
        rs = task.resource_stats()
        self.insights.record(
            {"signature": insights_mod.canonical_query(
                body.get("query")),
             "scored": insights_mod.scored_for_body(body),
             "took_ms": float(out.get("took", 0)),
             "execution_path": "scatter", "plan_cache": "none",
             "index": index},
            opaque_id=opaque_id,
            cpu_nanos=int(rs.get("cpu_time_in_nanos", 0)),
            heap_bytes=int(rs.get("peak_heap_size_in_bytes", 0)),
            outcome=outcome)
        return out

    def _merge_profiles(self, responses, resp_meta, profile_prov,
                        attempt, scatter_s, reduce_s, failures) -> dict:
        """Coordinator-side profile merge: each remote shard section is
        annotated with the copy that actually served it — chosen node,
        its current C3 rank and duress verdict, failover attempts, and
        the reroute/spill provenance recorded at copy-selection time —
        then a ``coordinator`` block adds the scatter/reduce split only
        this node can measure."""
        collector = self.response_collector
        sections = []
        for (node, shards), resp in zip(resp_meta, responses):
            rank = collector.rank(node)
            group = {
                "node": node,
                "shards": list(shards),
                "c3_rank": None if rank is None else round(rank, 3),
                "in_duress": collector.in_duress(node),
                "failover_attempts": max(
                    (attempt.get(s, 0) for s in shards), default=0),
            }
            if profile_prov is not None:
                prov = [dict(profile_prov.get(s) or {}, shard=s)
                        for s in shards if profile_prov.get(s)]
                if prov:
                    group["selection"] = prov
            for sec in (resp["resp"].get("profile") or {}) \
                    .get("shards") or []:
                sec = dict(sec)
                sec["shard_group"] = group
                sections.append(sec)
        return {
            "shards": sections,
            "coordinator": {
                "node": self.node_id,
                "scatter_time_in_nanos": int(scatter_s * 1e9),
                "reduce_time_in_nanos": int(reduce_s * 1e9),
                "sources": len(responses),
                "failed_shards": len(failures),
            },
        }

    def _h_search_shards(self, payload: dict) -> dict:
        from opensearch_tpu.common import tasks as taskmod

        svc = self.indices.get(payload["index"])
        if svc is None:
            raise ShardNotFoundError(
                f"[{payload['index']}] has no shards on this node")
        body = dict(payload.get("body") or {})
        explicit_cache = body.pop("request_cache", None)
        agg_partials = bool(payload.get("agg_partials"))
        shard_ids = sorted(payload["shards"])
        # the shard query phase runs as a registered child task: a
        # banned/cancelled parent stops it at the next segment boundary,
        # and its resource usage shows up in this node's task list
        opaque_id = payload.get("opaque_id")
        task = self.task_manager.register(
            A_SEARCH_SHARDS,
            f"shards {shard_ids} of [{payload['index']}]",
            parent_task_id=payload.get("parent_task_id"),
            headers={"X-Opaque-Id": opaque_id} if opaque_id else None)
        token = taskmod.set_current(task)
        start = time.monotonic()
        try:
            task.ensure_not_cancelled()    # parent already banned?
            # data-node insight scope: the shard query phase this node
            # executes is ITS workload evidence (the coordinator records
            # the scatter separately); records gain the task's CPU/heap
            # and the client attribution threaded through the payload
            with insights_mod.collecting() as sink:
                out = dict(self._search_shards_body(
                    svc, body, explicit_cache, agg_partials, shard_ids))
            task.record_checkpoint()
            rs = task.resource_stats()
            for rec in sink:
                self.insights.record(
                    rec, opaque_id=opaque_id,
                    cpu_nanos=int(rs.get("cpu_time_in_nanos", 0))
                    // max(1, len(sink)),
                    heap_bytes=int(rs.get(
                        "peak_heap_size_in_bytes", 0)))
            with self._lock:
                self._service_time_ewma.add(
                    (time.monotonic() - start) * 1e9)
            # piggyback AFTER the (byte-stable) cached body so load is
            # always current, never frozen into a cache entry
            out["node_load"] = self._load_stats()
            return out
        finally:
            taskmod.reset_current(token)
            self.task_manager.unregister(task)

    def _search_shards_body(self, svc, body, explicit_cache,
                            agg_partials, shard_ids) -> dict:

        def compute() -> dict:
            from opensearch_tpu.search.engine import query_engine
            from opensearch_tpu.search.executor import ShardSearcher
            segs = []
            for shard_id in shard_ids:
                engine = svc.engine_for(shard_id)
                segs.extend(engine.acquire_searcher().segments)
            searcher = ShardSearcher(segs, svc.mapper,
                                     index_name=svc.name)
            # the data-node query phase routes through the SAME unified
            # engine entry as the REST edge (no service handle: this
            # searcher is per-payload, so the mesh/batcher backends do
            # not apply — the engine runs the plain lowering pipeline)
            return {"resp": query_engine().execute(
                searcher, body, agg_partials=agg_partials)}

        # data-node request cache: remote coordinators' repeated query
        # phases hit here without re-executing (the hit/miss counts land
        # on THIS node's shard copies — key includes the local service's
        # uuid and reader generation)
        if svc.should_cache_request(body, explicit_cache, agg_partials):
            from opensearch_tpu.indices.request_cache import request_cache
            out, hit = request_cache().get_or_compute(
                index=svc.name, svc_uuid=svc.uuid,
                shard_key=",".join(map(str, shard_ids)),
                reader_gen=svc._reader_gen, body=body, compute=compute)
            if hit:
                insights_mod.emit(
                    signature=insights_mod.canonical_query(
                        body.get("query")),
                    scored=insights_mod.scored_for_body(body),
                    took_ms=float(out["resp"].get("took", 0)),
                    execution_path="cached", plan_cache="hit",
                    request_cache="hit", index=svc.name)
            else:
                insights_mod.annotate_last(request_cache="miss",
                                           index=svc.name)
        else:
            out = compute()
            insights_mod.annotate_last(request_cache="bypass",
                                       index=svc.name)
        svc._maybe_slowlog(body, out["resp"])
        return out

    # -- query insights fan-in ---------------------------------------------

    def _h_insights(self, payload: dict) -> dict:
        """Serve this node's insights section to a fanning-in
        coordinator."""
        return {"section": self.insights.section(
            by=payload.get("by", "latency"), n=payload.get("n"))}

    def top_queries(self, by: str = "latency",
                    n: Optional[int] = None) -> dict:
        """Cluster-wide ``_insights/top_queries``: fan the per-node
        sections in from every cluster member and merge them
        provenance-annotated (PR 9's profile-merge discipline — each
        entry names the node that recorded it; unreachable nodes are
        REPORTED in ``failed_nodes``, never silently dropped)."""
        n = self.insights.top_n if n is None else max(1, int(n))
        state = self.coordinator.state()
        sections: dict[str, dict] = {}
        for nid in sorted(state.nodes):
            if nid == self.node_id:
                sections[nid] = self.insights.section(by=by, n=n)
                continue
            try:
                resp = self.transport.send_request(
                    nid, A_INSIGHTS, {"by": by, "n": n}, timeout=5.0)
                sections[nid] = resp.get("section") or {
                    "error": "empty section"}
            except (OpenSearchTpuError, TimeoutError,
                    ConnectionError) as e:
                sections[nid] = {"error": f"{type(e).__name__}: {e}"}
        out = insights_mod.merge_sections(sections, by=by, n=n)
        out["coordinator"] = self.node_id
        return out

    # -- health / cat surfaces --------------------------------------------

    def cluster_health(self) -> dict:
        """Cluster-scope ``_cluster/health``: red when any shard group
        has no assigned primary or is flagged corrupted, yellow when
        replica slots are unfilled or out of sync, green otherwise.
        Local corruption markers ride along so a red verdict names its
        evidence."""
        state = self.coordinator.state()
        active = unassigned = corrupted = 0
        status = "green"
        for index, entries in state.routing.items():
            for e in entries:
                if e.get("primary"):
                    active += 1 + len(e.get("replicas") or [])
                else:
                    unassigned += 1
                    status = "red"
                if e.get("corrupted"):
                    corrupted += 1
                    status = "red"
                elif status == "green" and (
                        set(e.get("in_sync") or [])
                        != set(copies_of(e))):
                    status = "yellow"
        local_markers = {
            name: {str(s): m for s, m in svc.corrupted_shards().items()}
            for name, svc in self.indices.items()
            if svc.corrupted_shards()}
        if local_markers and status == "green":
            status = "red"
        out = {
            "cluster_name": state.cluster_name,
            "status": status,
            "number_of_nodes": len(state.nodes),
            "number_of_data_nodes": len(state.nodes),
            "active_shards": active,
            "unassigned_shards": unassigned,
            "corrupted_shards": corrupted + sum(
                len(v) for v in local_markers.values()),
        }
        if local_markers:
            out["corruption_markers"] = local_markers
        return out

    def cat_shards(self) -> list:
        """Cluster-scope ``_cat/shards`` rows: one per shard copy,
        including the search tier (``prirep`` "s") with its replication
        lag — the coordinator's recorded lag for remote searchers, the
        live value for this node's own copies."""
        state = self.coordinator.state()
        collector = self.response_collector
        rows = []
        for index in sorted(state.routing):
            for s, e in enumerate(state.routing[index]):
                if e.get("primary"):
                    rows.append({"index": index, "shard": str(s),
                                 "prirep": "p", "state": "STARTED",
                                 "node": e["primary"]})
                else:
                    rows.append({"index": index, "shard": str(s),
                                 "prirep": "p", "state": "UNASSIGNED",
                                 "node": None})
                in_sync = set(e.get("in_sync") or [])
                for r in e.get("replicas") or []:
                    rows.append({
                        "index": index, "shard": str(s), "prirep": "r",
                        "state": ("STARTED" if r in in_sync
                                  else "INITIALIZING"),
                        "node": r})
                ready = set(e.get("search_in_sync") or [])
                for r in e.get("search_replicas") or []:
                    lag = (self.shard_search_lag(index, s)
                           if r == self.node_id
                           else collector.search_lag(r))
                    rows.append({
                        "index": index, "shard": str(s), "prirep": "s",
                        "state": ("STARTED" if r in ready
                                  else "INITIALIZING"),
                        "node": r,
                        "search.lag": "-" if lag is None else str(lag)})
        return rows

    def cat_indices(self) -> list:
        """Cluster-scope ``_cat/indices`` rows with a real per-index
        health column (red on unassigned-primary/corruption)."""
        state = self.coordinator.state()
        rows = []
        for index in sorted(state.indices):
            entries = state.routing.get(index, [])
            health = "green"
            for e in entries:
                if not e.get("primary") or e.get("corrupted"):
                    health = "red"
                    break
                if set(e.get("in_sync") or []) != set(copies_of(e)):
                    health = "yellow"
            svc = self.indices.get(index)
            if svc is not None and svc.corrupted_shards():
                health = "red"
            meta = state.indices[index]
            rows.append({
                "health": health, "status": "open", "index": index,
                "pri": str(int((meta.get("settings") or {})
                               .get("number_of_shards", 1))),
                "rep": str(int((meta.get("settings") or {})
                               .get("number_of_replicas", 0))),
            })
        return rows

    # -- lifecycle ---------------------------------------------------------

    def start_election(self) -> bool:
        return self.coordinator.start_election()

    def start(self):
        self.coordinator.start()
        self.autoscaler.start()
        # duress must be detected BETWEEN admissions too: the monitor
        # thread evaluates the trackers on a cadence even when no new
        # searches arrive to tick them (previously admission-path-only,
        # so an idle-but-saturated node never noticed it recovered)
        self.search_backpressure.start_monitor()
        # periodic disk probe: an fsync that starts failing between
        # stats reads still flips fs_healthy, which the next
        # fault-detection ping carries to the leader
        self.fs_health.check()
        self.fs_health.start_probe(self.fs_health_interval,
                                   name=f"fshealth-{self.node_id}")
        return self

    def _handshake_peer(self, peer: str):
        try:
            self.transport.negotiated_version(peer)
        except OpenSearchTpuError as e:
            import logging
            logging.getLogger("opensearch_tpu.transport").warning(
                "handshake with [%s] failed: %s", peer, e)

    def stop(self):
        # idempotent: a test teardown stopping an already-stopped node
        # (or one whose start_election never ran) must return at once
        with self._lock:
            if getattr(self, "_node_stopped", False):
                return
            self._node_stopped = True
        # bounded join (stop_monitor joins with a timeout): node teardown
        # must never hang on the backpressure monitor thread
        self.autoscaler.stop()
        self.search_backpressure.stop_monitor()
        self.fs_health.stop_probe()
        # quiesce the (process-global) query-engine workers with a
        # bounded join; another live node's next search respawns them
        from opensearch_tpu.search.engine import query_engine
        query_engine().shutdown()
        self.coordinator.stop()
        with self._lock:
            for svc in self.indices.values():
                svc.close()
            self.indices.clear()
        self.transport.close()
