"""Remote store: per-shard segment mirroring into a blob repository —
durability without replicas.

Analog of the reference's remote store (ref
index/shard/RemoteStoreRefreshListener.java:56 upload-on-refresh,
index/store/RemoteSegmentStoreDirectory.java:77 the mirrored directory,
remotestore restore action).  On every flush the shard's committed
segment files upload content-addressed into the repository's shared
``blobs`` container (the snapshot dedup space, so remote store and
snapshots share bytes), and a per-shard ``manifest.json`` records the
commit.  Restore materializes shard directories straight from the
manifest — a lost node recovers its primaries with zero replicas
configured.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Optional

from opensearch_tpu.common.errors import (OpenSearchTpuError,
                                          ResourceNotFoundError)

_SEGMENT_SUFFIXES = (".npz", ".json", ".src", ".liv")


class RemoteStoreError(OpenSearchTpuError):
    status = 500


def shard_container(repo, index_name: str, shard_id) -> object:
    return repo.store.container(f"remote/{index_name}/{shard_id}")


def upload_segment_files(repo, seg_dir: str, segments: list,
                         strict: bool = True):
    """Content-addressed upload of a commit's segment files into the
    repository's shared blob space (used by BOTH remote store and
    snapshots — one dedup loop, one file-set definition).

    Returns (files, uploaded, reused).  ``strict`` raises when a core
    file vanished mid-iteration (a manifest listing missing files would
    make a restore unopenable); .liv is legitimately optional."""
    files = []
    uploaded = reused = 0
    for seg_id in segments:
        for suffix in _SEGMENT_SUFFIXES:
            path = os.path.join(seg_dir, seg_id + suffix)
            if not os.path.exists(path):
                if suffix != ".liv" and strict:
                    raise RemoteStoreError(
                        f"segment file [{seg_id}{suffix}] vanished "
                        "during upload — manifest not written")
                continue
            with open(path, "rb") as f:
                data = f.read()
            digest = hashlib.sha256(data).hexdigest()
            if repo.blobs.blob_exists(digest):
                reused += 1
            else:
                repo.blobs.write_blob(digest, data)
                uploaded += 1
            from opensearch_tpu.index.store import file_checksum
            files.append({"name": seg_id + suffix, "blob": digest,
                          "size": len(data),
                          # PR-8 integrity record: searchers pulling
                          # this blob verify the CRC before install
                          "crc32": file_checksum(data)["crc32"]})
    return files, uploaded, reused


def upload_shard(repo, index_name: str, shard_id, engine,
                 commit: dict, extra: Optional[dict] = None) -> dict:
    """Mirror one shard's commit point into the repository.  Called
    after ``engine.flush()`` with its commit dict; incremental by
    content hash (unchanged segments upload nothing).  ``extra`` keys
    (e.g. the search-tier checkpoint seq/term) ride in the manifest."""
    seg_dir = os.path.join(engine.data_path, "segments")
    files, uploaded, reused = upload_segment_files(
        repo, seg_dir, commit["segments"])
    manifest = {"commit": commit, "files": files}
    if extra:
        manifest.update(extra)
    shard_container(repo, index_name, shard_id).write_blob(
        "manifest.json", json.dumps(manifest).encode())
    return {"uploaded": uploaded, "reused": reused,
            "files": len(files), "file_metas": files}


def read_manifest(repo, index_name: str, shard_id) -> Optional[dict]:
    from opensearch_tpu.common.blobstore import NoSuchBlobError

    try:
        return json.loads(shard_container(
            repo, index_name, shard_id).read_blob("manifest.json"))
    except NoSuchBlobError:
        return None


def install_segment_files(seg_dir: str, files: list, read_blob,
                          on_corrupt=None) -> int:
    """Verify-and-materialize content-addressed blobs into a shard's
    segment directory — shared by remote-store restore and snapshot
    restore.  Every blob is re-hashed against its content address BEFORE
    any byte reaches a final file name (the dedup key doubles as the
    integrity check, like the reference re-verifying
    StoreFileMetadata checksums on restore); a mismatch raises via
    ``on_corrupt(name, blob)`` (default: RemoteStoreError).  Segment
    commit manifests are regenerated from the verified bytes so the
    restored store is checksum-verifiable from its first open."""
    import hashlib

    from opensearch_tpu.index import store as _store

    os.makedirs(seg_dir, exist_ok=True)
    entries: dict[str, dict] = {}
    for fmeta in files:
        name = fmeta["name"]
        validate_manifest_name(name)
        data = read_blob(fmeta["blob"])
        digest = hashlib.sha256(data).hexdigest()
        if digest != fmeta["blob"]:
            if on_corrupt is not None:
                raise on_corrupt(name, fmeta["blob"])
            raise RemoteStoreError(
                f"blob [{fmeta['blob']}] for [{name}] failed content "
                f"verification (sha256 [{digest}]) — not installing it")
        _store.write_durable(os.path.join(seg_dir, name), data)
        entries[name] = _store.file_checksum(data)
    by_seg: dict[str, dict] = {}
    for name, cksum in entries.items():
        for suffix in (".json", ".npz", ".src"):
            if name.endswith(suffix):
                by_seg.setdefault(name[: -len(suffix)], {})[name] = cksum
    for seg_id, seg_entries in sorted(by_seg.items()):
        if len(seg_entries) == 3:        # complete data-file set only
            _store.write_segment_manifest(seg_dir, seg_id, seg_entries)
    return len(files)


def validate_manifest_name(name: str) -> str:
    """Manifest-supplied file names join into the shard directory — the
    same rule FsBlobContainer._path enforces for blob names (no path
    separators, no leading dot) must hold here, or a tampered repository
    manifest writes outside the shard dir on restore/mount."""
    if ("/" in name or os.sep in name or (os.altsep and os.altsep in name)
            or name.startswith(".") or not name):
        from opensearch_tpu.common.errors import IllegalArgumentError
        raise IllegalArgumentError(
            f"invalid file name [{name}] in remote store manifest")
    return name


def restore_shard(repo, index_name: str, shard_id,
                  shard_dir: str) -> dict:
    """Materialize a shard directory from its remote manifest (the
    remotestore restore action's per-shard step)."""
    manifest = read_manifest(repo, index_name, shard_id)
    if manifest is None:
        raise ResourceNotFoundError(
            f"no remote store manifest for [{index_name}][{shard_id}]")
    seg_dir = os.path.join(shard_dir, "segments")
    install_segment_files(seg_dir, manifest["files"], repo.blobs.read_blob)
    commit = dict(manifest["commit"])
    tmp = os.path.join(shard_dir, "commit.json.tmp")
    with open(tmp, "w") as f:
        json.dump(commit, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(shard_dir, "commit.json"))
    return {"files": len(manifest["files"])}
