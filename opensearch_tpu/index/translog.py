"""Per-shard write-ahead log.

Analog of ``index/translog/Translog.java`` (add :541, ensureSynced :821,
rollGeneration :1703) and ``TranslogWriter``/``Checkpoint``: operations are
appended to a generation file before being acknowledged, fsynced per the
durability policy, and replayed on recovery for every op newer than the
last commit's max seq-no.

Format: one op per line — ``<crc32 hex 8>`` + JSON payload.  A checkpoint
file records the current generation and the minimum generation still
needed (everything below was committed into segments).  Torn tails (a
partial last line after kill -9) are detected by the CRC and discarded,
like the reference's checksummed operation framing.
"""

from __future__ import annotations

import json
import os
import threading
import zlib
from typing import Iterator, Optional

from opensearch_tpu.common.errors import OpenSearchTpuError


class TranslogCorruptedError(OpenSearchTpuError):
    status = 500


class Translog:
    CHECKPOINT = "translog.ckp"

    def __init__(self, path: str, durability: str = "request"):
        """durability: ``request`` = fsync on every sync() call (the caller
        syncs before acking), ``async`` = fsync only on roll/close (the
        engine's async fsync interval syncs periodically)."""
        self.path = path
        self.durability = durability
        os.makedirs(path, exist_ok=True)
        ckp = self._read_checkpoint()
        if ckp is None:
            self.generation = 1
            self.min_generation = 1
            self._write_checkpoint()
        else:
            self.generation = ckp["generation"]
            self.min_generation = ckp["min_generation"]
        # a torn tail (kill -9 mid-append) must be truncated BEFORE we
        # append again, or the next op would merge with the garbage bytes
        # into one bad-CRC line and a later recovery would drop it.
        # synced_offset = bytes of the active generation known durable
        # (below it corruption means acked data loss -> raise; at/past it
        # the ops were never acked, so truncation is always safe).
        synced = 0
        if ckp is not None and ckp.get("generation") == self.generation:
            synced = int(ckp.get("synced_offset", 0))
        self._truncate_torn_tail(self._gen_path(self.generation), synced)
        # append-only WAL: durability comes from sync()'s fsync +
        # checkpoint high-water mark, CRC recovery # non-durable-ok
        self._file = open(self._gen_path(self.generation), "ab")
        self._synced_offset = synced
        self._ops_since_sync = 0
        # serializes sync()'s fsync + checkpoint replace: concurrent
        # write RPCs each call ensure_synced() before acking, and two
        # unserialized checkpoint writers race the same .ckp.tmp rename
        # (found by the chaos-soak harness's concurrent bulk workload)
        self._sync_lock = threading.Lock()

    @staticmethod
    def _truncate_torn_tail(path: str, synced_offset: int = 0):
        """Truncate a torn tail so the generation can be appended to again.

        Corruption BELOW ``synced_offset`` (the fsync high-water mark from
        the checkpoint) followed by a later valid record means acked ops
        would be silently discarded by truncation — raise instead
        (reference: TranslogCorruptedException for non-tail corruption).
        Corruption at/past the synced offset was never acked: out-of-order
        page writeback can persist a later unacked op but not an earlier
        one, so truncating from the first bad byte is always safe there."""
        if not os.path.exists(path):
            if synced_offset > 0:
                raise TranslogCorruptedError(
                    f"translog [{path}] is missing but its checkpoint "
                    f"records {synced_offset} fsynced bytes")
            return

        def line_ok(line: bytes) -> bool:
            if len(line) < 8:
                return False
            try:
                expected = int(line[:8], 16)
            except ValueError:
                return False
            return (zlib.crc32(line[8:]) & 0xFFFFFFFF) == expected

        with open(path, "rb") as f:
            data = f.read()
        good_end = 0
        first_bad = None
        pos = 0
        while pos < len(data):
            nl = data.find(b"\n", pos)
            line = data[pos: nl if nl >= 0 else len(data)]
            terminated = nl >= 0
            if not line and terminated:   # blank line, keep walking
                if first_bad is None:
                    good_end = nl + 1
                pos = nl + 1
                continue
            if terminated and line_ok(line):
                if first_bad is None:
                    good_end = nl + 1
                # else: bad region followed by valid ops — handled below
                # (fatal iff the bad region starts below the fsync mark)
            else:
                # bad or unterminated line: candidate torn tail
                if first_bad is None:
                    first_bad = pos
            pos = nl + 1 if terminated else len(data)
        if len(data) < synced_offset:
            raise TranslogCorruptedError(
                f"translog [{path}] is shorter ({len(data)}) than its fsync "
                f"high-water mark ({synced_offset}) — acked ops are missing")
        if first_bad is not None and first_bad < synced_offset:
            # corruption inside the acked region — whether or not valid
            # records follow, truncating would silently drop fsynced ops
            raise TranslogCorruptedError(
                f"translog [{path}] is corrupt at byte [{first_bad}] below "
                f"the fsync high-water mark ({synced_offset}) — acked ops "
                "are corrupt, refusing to truncate them away")
        if good_end < len(data):
            # in-place truncation of an UNACKED tail: the fsync below
            # persists it; rename can't shorten # non-durable-ok
            with open(path, "r+b") as f:
                f.truncate(good_end)
                f.flush()
                os.fsync(f.fileno())

    # -- paths / checkpoint ----------------------------------------------

    def _gen_path(self, gen: int) -> str:
        return os.path.join(self.path, f"translog-{gen}.log")

    def _read_checkpoint(self) -> Optional[dict]:
        p = os.path.join(self.path, self.CHECKPOINT)
        if not os.path.exists(p):
            return None
        with open(p) as f:
            return json.load(f)

    def _write_checkpoint(self):
        p = os.path.join(self.path, self.CHECKPOINT)
        tmp = p + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"generation": self.generation,
                       "min_generation": self.min_generation,
                       "synced_offset": getattr(self, "_synced_offset", 0)},
                      f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, p)

    # -- write path -------------------------------------------------------

    @staticmethod
    def encode(op: dict) -> bytes:
        """Serialize an op up front so callers can fail BEFORE mutating any
        engine state (write-path atomicity)."""
        return json.dumps(op, separators=(",", ":")).encode()

    def add(self, op: dict):
        """Append one operation (no fsync — call sync() before acking)."""
        self.add_encoded(self.encode(op))

    def add_encoded(self, payload: bytes):
        crc = zlib.crc32(payload) & 0xFFFFFFFF
        self._file.write(f"{crc:08x}".encode() + payload + b"\n")
        self._ops_since_sync += 1

    def sync(self):
        """Durability barrier (ensureSynced analog).  Advances the fsync
        high-water mark in the checkpoint, like the reference's per-sync
        Checkpoint file — recovery uses it to tell acked-data corruption
        (fatal) from unacked-tail garbage (truncatable)."""
        with self._sync_lock:
            if self._ops_since_sync == 0 and \
                    self._synced_offset == self._file.tell():
                return   # already durable: skip the double fsync per op
            from opensearch_tpu.common.telemetry import metrics
            with metrics().time_ms("translog.sync_ms"):
                self._file.flush()
                os.fsync(self._file.fileno())
                self._synced_offset = self._file.tell()
                self._ops_since_sync = 0
                self._write_checkpoint()

    def roll_generation(self):
        """Start a new generation file (pre-commit, rollGeneration analog)."""
        self.sync()
        self._file.close()
        self.generation += 1
        # non-durable-ok: fresh append-only generation (see __init__)
        self._file = open(self._gen_path(self.generation), "ab")
        self._synced_offset = 0
        self._write_checkpoint()

    def trim_above(self, seq_no: int):
        """Append a trim marker: retained ops with ``seq_no`` ABOVE the cut
        are dropped on replay (Translog.trimOperations /
        trimOperationsOfPreviousPrimaryTerms analog).  Used when a deposed
        primary (or a divergent replica) rolls back ops above the global
        checkpoint before rejoining the new primary's lineage — the WAL
        stays append-only, so the rollback itself is as durable as the ops
        it cancels."""
        self.add({"_trim_above": int(seq_no)})
        self.sync()

    def trim(self, min_generation: int):
        """Delete generations below ``min_generation`` (post-commit)."""
        min_generation = min(min_generation, self.generation)
        for gen in range(self.min_generation, min_generation):
            p = self._gen_path(gen)
            if os.path.exists(p):
                os.remove(p)
        self.min_generation = min_generation
        self._write_checkpoint()

    def close(self):
        if not self._file.closed:
            self.sync()
            self._file.close()

    # -- recovery ---------------------------------------------------------

    def read_ops(self, min_seq_no: int = -1) -> Iterator[dict]:
        """Replay all retained ops with seq_no > min_seq_no, oldest first.
        A corrupt NON-tail line raises; a corrupt tail (torn final write)
        is discarded silently, matching reference recovery semantics.
        ``_trim_above`` markers (see trim_above) cancel earlier retained
        ops above their cut and are never yielded themselves — a resync op
        re-written at the same seq under the new term lands after the
        marker, so replay converges on the post-rollback state."""
        buffered: list[dict] = []
        for gen in range(self.min_generation, self.generation + 1):
            p = self._gen_path(gen)
            if not os.path.exists(p):
                continue
            if gen == self.generation and not self._file.closed:
                self._file.flush()
            with open(p, "rb") as f:
                lines = f.read().split(b"\n")
            for i, line in enumerate(lines):
                if not line:
                    continue
                is_tail = (gen == self.generation and i >= len(lines) - 2)
                if len(line) < 8:
                    if is_tail:
                        break
                    raise TranslogCorruptedError(
                        f"translog generation [{gen}] line [{i}] truncated")
                crc_hex, payload = line[:8], line[8:]
                try:
                    expected = int(crc_hex, 16)
                except ValueError:
                    if is_tail:
                        break
                    raise TranslogCorruptedError(
                        f"translog generation [{gen}] line [{i}] bad header")
                if (zlib.crc32(payload) & 0xFFFFFFFF) != expected:
                    if is_tail:
                        break
                    raise TranslogCorruptedError(
                        f"translog generation [{gen}] line [{i}] checksum mismatch")
                op = json.loads(payload)
                if "_trim_above" in op:
                    cut = int(op["_trim_above"])
                    buffered = [o for o in buffered
                                if o.get("seq_no", -1) <= cut]
                    continue
                buffered.append(op)
        for op in buffered:
            if op.get("seq_no", -1) > min_seq_no:
                yield op

    def ops_count(self) -> int:
        return sum(1 for _ in self.read_ops())
