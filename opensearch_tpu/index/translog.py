"""Per-shard write-ahead log.

Analog of ``index/translog/Translog.java`` (add :541, ensureSynced :821,
rollGeneration :1703) and ``TranslogWriter``/``Checkpoint``: operations are
appended to a generation file before being acknowledged, fsynced per the
durability policy, and replayed on recovery for every op newer than the
last commit's max seq-no.

Format: one op per line — ``<crc32 hex 8>`` + JSON payload.  A checkpoint
file records the current generation and the minimum generation still
needed (everything below was committed into segments).  Torn tails (a
partial last line after kill -9) are detected by the CRC and discarded,
like the reference's checksummed operation framing.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Iterator, Optional

from opensearch_tpu.common.errors import OpenSearchTpuError


class TranslogCorruptedError(OpenSearchTpuError):
    status = 500


class Translog:
    CHECKPOINT = "translog.ckp"

    def __init__(self, path: str, durability: str = "request"):
        """durability: ``request`` = fsync on every sync() call (the caller
        syncs before acking), ``async`` = fsync only on roll/close (the
        engine's async fsync interval syncs periodically)."""
        self.path = path
        self.durability = durability
        os.makedirs(path, exist_ok=True)
        ckp = self._read_checkpoint()
        if ckp is None:
            self.generation = 1
            self.min_generation = 1
            self._write_checkpoint()
        else:
            self.generation = ckp["generation"]
            self.min_generation = ckp["min_generation"]
        # a torn tail (kill -9 mid-append) must be truncated BEFORE we
        # append again, or the next op would merge with the garbage bytes
        # into one bad-CRC line and a later recovery would drop it
        self._truncate_torn_tail(self._gen_path(self.generation))
        self._file = open(self._gen_path(self.generation), "ab")
        self._ops_since_sync = 0

    @staticmethod
    def _truncate_torn_tail(path: str):
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            data = f.read()
        good_end = 0
        pos = 0
        while pos < len(data):
            nl = data.find(b"\n", pos)
            if nl < 0:
                break                    # unterminated tail
            line = data[pos:nl]
            if len(line) >= 8:
                try:
                    expected = int(line[:8], 16)
                except ValueError:
                    break
                if (zlib.crc32(line[8:]) & 0xFFFFFFFF) != expected:
                    break
                good_end = nl + 1
            elif line:
                break
            else:
                good_end = nl + 1        # blank line, keep walking
            pos = nl + 1
        if good_end < len(data):
            with open(path, "r+b") as f:
                f.truncate(good_end)
                f.flush()
                os.fsync(f.fileno())

    # -- paths / checkpoint ----------------------------------------------

    def _gen_path(self, gen: int) -> str:
        return os.path.join(self.path, f"translog-{gen}.log")

    def _read_checkpoint(self) -> Optional[dict]:
        p = os.path.join(self.path, self.CHECKPOINT)
        if not os.path.exists(p):
            return None
        with open(p) as f:
            return json.load(f)

    def _write_checkpoint(self):
        p = os.path.join(self.path, self.CHECKPOINT)
        tmp = p + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"generation": self.generation,
                       "min_generation": self.min_generation}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, p)

    # -- write path -------------------------------------------------------

    @staticmethod
    def encode(op: dict) -> bytes:
        """Serialize an op up front so callers can fail BEFORE mutating any
        engine state (write-path atomicity)."""
        return json.dumps(op, separators=(",", ":")).encode()

    def add(self, op: dict):
        """Append one operation (no fsync — call sync() before acking)."""
        self.add_encoded(self.encode(op))

    def add_encoded(self, payload: bytes):
        crc = zlib.crc32(payload) & 0xFFFFFFFF
        self._file.write(f"{crc:08x}".encode() + payload + b"\n")
        self._ops_since_sync += 1

    def sync(self):
        """Durability barrier (ensureSynced analog)."""
        self._file.flush()
        os.fsync(self._file.fileno())
        self._ops_since_sync = 0

    def roll_generation(self):
        """Start a new generation file (pre-commit, rollGeneration analog)."""
        self.sync()
        self._file.close()
        self.generation += 1
        self._file = open(self._gen_path(self.generation), "ab")
        self._write_checkpoint()

    def trim(self, min_generation: int):
        """Delete generations below ``min_generation`` (post-commit)."""
        min_generation = min(min_generation, self.generation)
        for gen in range(self.min_generation, min_generation):
            p = self._gen_path(gen)
            if os.path.exists(p):
                os.remove(p)
        self.min_generation = min_generation
        self._write_checkpoint()

    def close(self):
        if not self._file.closed:
            self.sync()
            self._file.close()

    # -- recovery ---------------------------------------------------------

    def read_ops(self, min_seq_no: int = -1) -> Iterator[dict]:
        """Replay all retained ops with seq_no > min_seq_no, oldest first.
        A corrupt NON-tail line raises; a corrupt tail (torn final write)
        is discarded silently, matching reference recovery semantics."""
        for gen in range(self.min_generation, self.generation + 1):
            p = self._gen_path(gen)
            if not os.path.exists(p):
                continue
            if gen == self.generation and not self._file.closed:
                self._file.flush()
            with open(p, "rb") as f:
                lines = f.read().split(b"\n")
            for i, line in enumerate(lines):
                if not line:
                    continue
                is_tail = (gen == self.generation and i >= len(lines) - 2)
                if len(line) < 8:
                    if is_tail:
                        break
                    raise TranslogCorruptedError(
                        f"translog generation [{gen}] line [{i}] truncated")
                crc_hex, payload = line[:8], line[8:]
                try:
                    expected = int(crc_hex, 16)
                except ValueError:
                    if is_tail:
                        break
                    raise TranslogCorruptedError(
                        f"translog generation [{gen}] line [{i}] bad header")
                if (zlib.crc32(payload) & 0xFFFFFFFF) != expected:
                    if is_tail:
                        break
                    raise TranslogCorruptedError(
                        f"translog generation [{gen}] line [{i}] checksum mismatch")
                op = json.loads(payload)
                if op.get("seq_no", -1) > min_seq_no:
                    yield op

    def ops_count(self) -> int:
        return sum(1 for _ in self.read_ops())
