"""Quantized device index codec: int8/int16 impacts + bit-packed doc ids.

The f32 device layout (PR 5/11) spends 12 bytes per posting on the
scored term-bag path: 4 (doc_ids) + 4 (tfs, unused by impact scoring)
+ 4 (f32 impacts).  At 1M-10M docs that footprint is what parks the
corpus off the device.  This codec is the compressed alternative, after
the Lucene quantized-impacts line (arxiv 0911.5046) and BM25S's eager
impact layout (arxiv 2407.03618):

- **Quantized impacts** — per-posting impacts become int8 (or int16)
  codes with a per-term scale factor ``scales[t] = mx[t] / qmax`` where
  ``mx`` is the existing per-term block-max metadata.  Quantization is
  truncating (``floor``) with a floor of 1, so a dequantized impact
  never exceeds the term's block max — ``plan.max_score_bound``'s
  pruning bounds stay conservative unchanged — and never hits exact
  zero, so ``scores > 0 == matched`` fast-path semantics survive.
- **Exact-rank-parity guard** — every term block is dequantized and
  compared against the f32 ranking (score-desc, doc-asc — lax.top_k's
  tie-break).  A term whose quantized ranking diverges falls back to
  exact f32 storage for that block (CSR ``exact_vals``/``exact_offsets``),
  so single-term rankings are rank-identical to f32 *by construction*,
  not by hope.
- **Bit-packed doc ids** — postings store ``doc - base[term]`` deltas
  at a fixed segment-granular bit width, unpacked on device with two
  aligned uint32 reads per lane (random access preserved — the gather
  kernels stay shape-static, no prefix-sum decode).

The lowering policy (``use_quantized``) decides per segment: "auto"
quantizes segments at/above ``QUANTIZED_MIN_DOCS`` so existing
small-corpus behavior is byte-identical, "on"/"off" force either path
(tests pin both).  ``tools/check_quantized_staging.py`` (tier-1) keeps
f32 impact staging from sneaking back outside this codec and the pager.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

# Lowering policy knobs (dynamic settings land here via node.py, the
# engine-module-global idiom): "auto" quantizes only large segments,
# "on"/"off" force the path.  QUANTIZED_MIN_DOCS keeps every existing
# small-corpus test on the byte-identical f32 layout.
QUANTIZED_MODE = "auto"            # "auto" | "on" | "off"
QUANTIZED_MIN_DOCS = 65536
QUANTIZED_DTYPE = "int8"           # "int8" | "int16"

_QMAX = {"int8": 127, "int16": 32767}
_NP_DTYPE = {"int8": np.int8, "int16": np.int16}


def use_quantized(seg) -> bool:
    """Per-segment lowering decision: does this segment's scored
    term-bag path run on the quantized/paged layout?  Deterministic
    from segment size + module policy, so the device kernel and the
    byte-identical host fallback always agree on which table to read."""
    if QUANTIZED_MODE == "on":
        return True
    if QUANTIZED_MODE == "off":
        return False
    return int(getattr(seg, "n_docs", 0)) >= int(QUANTIZED_MIN_DOCS)


def _rank_order(vals: np.ndarray, docs: np.ndarray) -> np.ndarray:
    """Ranking a scorer induces on one postings list: score desc, then
    doc id asc — exactly ``lax.top_k``'s lower-index tie-break."""
    return np.lexsort((docs, -vals.astype(np.float64)))


@dataclass
class QuantizedPostings:
    """One (segment, field, avgdl) quantized table set.

    ``qvals``/``scales`` are the quantized impact column; terms whose
    quantized ranking broke parity store their f32 impacts sparsely in
    ``exact_vals`` at ``exact_offsets[t]:exact_offsets[t+1]`` (same
    in-list order as the postings CSR).  ``packed``/``base``/``width``
    are the bit-packed doc ids.  Everything is host numpy; staging to
    the device goes through the pager (``DeviceSegment.quantized``)."""

    qvals: np.ndarray                  # int8/int16 [P]
    scales: np.ndarray                 # f32 [T]
    exact_vals: np.ndarray             # f32 [E]
    exact_offsets: np.ndarray          # int32 [T+1]
    packed: np.ndarray                 # uint32 [W]
    base: np.ndarray                   # int32 [T]
    width: int
    dtype: str = "int8"
    avgdl: float = 0.0
    stats: dict = field(default_factory=dict)
    _deq: np.ndarray | None = None

    @property
    def nbytes(self) -> int:
        # an int attribute (numpy-style), not a method: cache weighers
        # read ``.nbytes`` off cached values directly
        return int(self.qvals.nbytes + self.scales.nbytes
                   + self.exact_vals.nbytes + self.exact_offsets.nbytes
                   + self.packed.nbytes + self.base.nbytes)

    def dequantized(self) -> np.ndarray:
        """Per-posting f32 impacts as the DEVICE kernel reconstructs
        them (``q.astype(f32) * scale``, exact blocks overridden) — the
        byte-parity source for ``TermBagPlan.host_topk`` on quantized
        segments.  Cached: host fallback under eviction is a hot path."""
        if self._deq is None:
            T = len(self.scales)
            lens = np.diff(self.exact_offsets)
            scale_of = np.repeat(self.scales,
                                 self._df()) if T else np.zeros(
                0, np.float32)
            deq = self.qvals.astype(np.float32) * scale_of
            if lens.sum():
                starts = self._offsets[:-1]
                for t in np.nonzero(lens)[0]:
                    e0, e1 = (int(self.exact_offsets[t]),
                              int(self.exact_offsets[t + 1]))
                    p0 = int(starts[t])
                    deq[p0: p0 + (e1 - e0)] = self.exact_vals[e0:e1]
            self._deq = deq
        return self._deq

    def _df(self) -> np.ndarray:
        return np.diff(self._offsets)

    # set by quantize_postings (not persisted; reload recomputes from
    # the segment's own offsets)
    _offsets: np.ndarray = None


def quantize_impacts(imp: np.ndarray, mx: np.ndarray,
                     offsets: np.ndarray, doc_ids: np.ndarray,
                     dtype: str = "int8"):
    """Quantize one field's per-posting impact column with the
    exact-rank-parity guard.

    Returns ``(qvals, scales, exact_vals, exact_offsets, stats)``.
    Truncating quantization with a floor of 1: ``q = clip(floor(imp /
    scale), 1, qmax)`` so (a) ``q * scale <= mx[t]`` — the block-max
    pruning bound holds unchanged — and (b) matched docs never decode
    to a zero contribution.  Terms whose dequantized ranking (score
    desc, doc asc) differs from f32 fall back to exact storage."""
    qmax = _QMAX[dtype]
    np_dt = _NP_DTYPE[dtype]
    T = len(offsets) - 1
    P = len(imp)
    scales = np.where(mx > 0, mx / np.float32(qmax), 1.0
                      ).astype(np.float32)
    scale_of = np.repeat(scales, np.diff(offsets)) if P else np.zeros(
        0, np.float32)
    q = np.clip(np.floor(imp / scale_of), 1, qmax) if P else np.zeros(
        0, np.float64)
    qvals = q.astype(np_dt)
    deq = qvals.astype(np.float32) * scale_of
    exact_lens = np.zeros(T, np.int32)
    exact_terms = []
    for t in range(T):
        e0, e1 = int(offsets[t]), int(offsets[t + 1])
        if e1 - e0 < 2:
            continue                # a 0/1-entry list cannot misrank
        docs = doc_ids[e0:e1]
        if np.array_equal(_rank_order(imp[e0:e1], docs),
                          _rank_order(deq[e0:e1], docs)):
            continue
        exact_lens[t] = e1 - e0
        exact_terms.append(t)
    exact_offsets = np.zeros(T + 1, np.int32)
    exact_offsets[1:] = np.cumsum(exact_lens)
    exact_vals = np.zeros(int(exact_offsets[-1]), np.float32)
    for t in exact_terms:
        e0, e1 = int(offsets[t]), int(offsets[t + 1])
        x0 = int(exact_offsets[t])
        exact_vals[x0: x0 + (e1 - e0)] = imp[e0:e1]
    stats = {"terms": T, "postings": P,
             "exact_terms": len(exact_terms),
             "exact_postings": int(exact_offsets[-1]),
             "dtype": dtype}
    return qvals, scales, exact_vals, exact_offsets, stats


def pack_doc_ids(doc_ids: np.ndarray, offsets: np.ndarray):
    """Delta-from-term-base + fixed-width bit pack at segment
    granularity.

    ``base[t]`` is the term's first doc id (doc ids ascend within one
    postings list, so every delta is non-negative); ``width`` is one
    segment-wide bit width — the max delta's bit length — so any
    posting decodes with two aligned uint32 reads (random access, no
    prefix-sum chain).  Returns ``(packed uint32 [W], base int32 [T],
    width)``; ``packed`` carries one guard word so lane ``w+1`` reads
    never go out of bounds."""
    T = len(offsets) - 1
    P = len(doc_ids)
    base = np.zeros(T, np.int32)
    lens = np.diff(offsets)
    nz = lens > 0
    base[nz] = doc_ids[offsets[:-1][nz]]
    deltas = (doc_ids.astype(np.int64)
              - np.repeat(base, lens).astype(np.int64)) if P else \
        np.zeros(0, np.int64)
    if P and deltas.min() < 0:
        raise ValueError("doc ids must ascend within a postings list")
    max_delta = int(deltas.max()) if P else 0
    width = max(1, int(max_delta).bit_length())
    if width > 31:
        raise ValueError(f"doc-id delta needs {width} bits (> 31)")
    n_words = (P * width + 31) // 32 + 1     # +1 guard word
    packed = np.zeros(n_words, np.uint32)
    if P:
        bitpos = np.arange(P, dtype=np.int64) * width
        word = (bitpos >> 5).astype(np.int64)
        off = (bitpos & 31).astype(np.uint64)
        val = deltas.astype(np.uint64) << off      # spans <= 2 words
        lo = (val & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        hi = (val >> np.uint64(32)).astype(np.uint32)
        np.bitwise_or.at(packed, word, lo)
        np.bitwise_or.at(packed, word + 1, hi)
    return packed, base, width


def unpack_doc_ids(packed: np.ndarray, base: np.ndarray,
                   offsets: np.ndarray, width: int) -> np.ndarray:
    """Host-side full decode (tests + the corruption-matrix verify):
    the numpy mirror of the device lane decode in ops/quantized.py."""
    T = len(offsets) - 1
    P = int(offsets[-1])
    if P == 0:
        return np.zeros(0, np.int32)
    idx = np.arange(P, dtype=np.int64)
    bitpos = idx * width
    w = (bitpos >> 5).astype(np.int64)
    off = (bitpos & 31).astype(np.uint64)
    pair = (packed[w].astype(np.uint64)
            | (packed[w + 1].astype(np.uint64) << np.uint64(32)))
    mask = np.uint64((1 << width) - 1)
    deltas = ((pair >> off) & mask).astype(np.int64)
    tid_of = np.repeat(np.arange(T, dtype=np.int64), np.diff(offsets))
    return (base[tid_of].astype(np.int64) + deltas).astype(np.int32)


def quantize_postings(pf, imp: np.ndarray, mx: np.ndarray,
                      avgdl: float,
                      dtype: str | None = None) -> QuantizedPostings:
    """Build the full quantized table set for one field's postings
    (``pf`` is a ``PostingsField``) from its f32 impact table."""
    dtype = dtype or QUANTIZED_DTYPE
    qvals, scales, exact_vals, exact_offsets, stats = quantize_impacts(
        imp, mx, pf.offsets, pf.doc_ids, dtype)
    packed, base, width = pack_doc_ids(pf.doc_ids, pf.offsets)
    f32_bytes = int(pf.doc_ids.nbytes + pf.tfs.nbytes + imp.nbytes)
    qt = QuantizedPostings(
        qvals=qvals, scales=scales, exact_vals=exact_vals,
        exact_offsets=exact_offsets, packed=packed, base=base,
        width=width, dtype=dtype, avgdl=float(np.float32(avgdl)),
        stats=stats)
    qt._offsets = pf.offsets
    qt.stats.update({"width": width, "f32_bytes": f32_bytes,
                     "quant_bytes": qt.nbytes})
    return qt
