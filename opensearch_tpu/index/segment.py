"""Immutable, array-oriented index segments — the TPU-native analog of a
Lucene segment.

Where Lucene stores postings as compressed blocks decoded doc-at-a-time
inside ``Weight.bulkScorer`` (ref server/src/main/java/org/opensearch/
search/internal/ContextIndexSearcher.java:318), a TPU segment is a set of
flat device-stageable arrays:

- per indexed field, CSR postings ``[term_offsets, doc_ids, tfs]`` plus a
  positions CSR (for phrase queries) and per-doc field lengths (BM25 norms
  — ref index/similarity/, Lucene BM25Similarity);
- per doc-value field, a multi-valued CSR column (SortedNumericDocValues /
  SortedSetDocValues analog — ref index/fielddata/) with an expanded
  ``value_docs`` row-id array so range masks and aggregations are single
  scatter ops on device, plus dense min/max columns for sorting;
- dense vectors as a ``[n_docs, dim]`` matrix (KnnVectorField analog);
- stored ``_source`` bytes host-side (ref index/mapper/SourceFieldMapper);
- a mutable live-docs bitmap for deletes (Lucene liveDocs analog).

All device arrays are padded to power-of-two sizes so XLA compile caches
are shared across segments of similar size (static shapes; see
/opt/skills/guides/pallas_guide.md on shape bucketing).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field as dc_field
from typing import Optional

import numpy as np

from opensearch_tpu.mapping.mapper import ParsedDocument

# Sentinels for missing values in dense sort columns.
LONG_MISSING_MAX = np.iinfo(np.int64).max
LONG_MISSING_MIN = np.iinfo(np.int64).min


def pad_pow2(n: int, minimum: int = 8) -> int:
    """Next power of two >= max(n, minimum)."""
    m = max(int(n), minimum)
    return 1 << (m - 1).bit_length()


def pad_bucket(n: int, minimum: int = 4096) -> int:
    """Coarse size bucket: ``minimum * 4^k``.  Used for per-query gather
    budgets, where every distinct value is a separate XLA compile — on a
    TPU behind a tunnel each compile costs tens of seconds, so 4x steps
    (vs pow2) trade a few wasted gather lanes for ~half the program
    count."""
    m = max(int(n), minimum)
    b = int(minimum)
    while b < m:
        b <<= 2
    return b


@dataclass
class PostingsField:
    """CSR inverted index for one field.

    ``offsets[t]:offsets[t+1]`` is term t's posting range in ``doc_ids`` /
    ``tfs``; ``pos_offsets[p]:pos_offsets[p+1]`` is posting entry p's range
    in ``positions``.  ``doc_lens`` is the per-doc token count (1.0 for
    fields without norms, like Lucene omitNorms keyword fields).
    """

    terms: dict[str, int]            # term -> term id (sorted order)
    df: np.ndarray                   # int32 [T] doc freq
    offsets: np.ndarray              # int32 [T+1]
    doc_ids: np.ndarray              # int32 [P]
    tfs: np.ndarray                  # float32 [P]
    pos_offsets: np.ndarray          # int32 [P+1]
    positions: np.ndarray            # int32 [sum positions]
    doc_lens: np.ndarray             # float32 [n_docs]
    total_len: float                 # sum of doc_lens over docs with field
    docs_with_field: int             # docs with >=1 term (Lucene docCount)
    has_norms: bool
    # docs where the field was present at all — a zero-token text value
    # still writes a "norm entry" (Lucene FieldExistsQuery over norms
    # matches it even though docCount does not count it).
    present: np.ndarray = None       # bool [n_docs]

    def term_id(self, term: str) -> int:
        return self.terms.get(term, -1)


@dataclass
class NumericDV:
    """Multi-valued numeric doc-value column (SortedNumericDocValues)."""

    kind: str                        # "long" | "double"
    offsets: np.ndarray              # int32 [n_docs+1]
    values: np.ndarray               # int64 | float64 [V], sorted per doc
    value_docs: np.ndarray           # int32 [V] owning doc per value
    minv: np.ndarray                 # dense per-doc min (sentinel if missing)
    maxv: np.ndarray                 # dense per-doc max
    exists: np.ndarray               # bool [n_docs]


@dataclass
class OrdinalDV:
    """Multi-valued ordinal column (SortedSetDocValues analog).  Ordinals
    are per-segment, assigned in sorted term order so ordinal comparisons
    are term-order comparisons."""

    ord_terms: list[str]             # ordinal -> term
    term_to_ord: dict[str, int]
    offsets: np.ndarray              # int32 [n_docs+1]
    ords: np.ndarray                 # int32 [V], sorted per doc
    value_docs: np.ndarray           # int32 [V]
    min_ord: np.ndarray              # int32 [n_docs] (-1 if missing)
    max_ord: np.ndarray              # int32 [n_docs]
    exists: np.ndarray               # bool [n_docs]


@dataclass
class VectorDV:
    values: np.ndarray               # float32 [n_docs, dim]
    exists: np.ndarray               # bool [n_docs]
    dim: int
    similarity: str                  # l2_norm | cosine | dot_product


@dataclass
class NestedBlock:
    """One nested path's objects, stored OBJECT-major: columns key by
    object id, ``obj_to_doc`` maps objects back to parents (the TPU
    formulation of Lucene's adjacent nested documents — ref
    index/mapper/ nested handling, join/ToParentBlockJoinQuery)."""

    obj_to_doc: np.ndarray               # int32 [n_obj]
    # child full path -> (values f64 [V], value_objs i32 [V])
    numeric: dict[str, tuple] = dc_field(default_factory=dict)
    # child full path -> (ord_terms list, ords i32 [V], value_objs i32)
    ordinal: dict[str, tuple] = dc_field(default_factory=dict)

    @property
    def n_objs(self) -> int:
        return len(self.obj_to_doc)


@dataclass
class GeoDV:
    offsets: np.ndarray              # int32 [n_docs+1]
    lats: np.ndarray                 # float32 [V]
    lons: np.ndarray                 # float32 [V]
    value_docs: np.ndarray           # int32 [V]
    exists: np.ndarray               # bool [n_docs]


class Segment:
    """One immutable segment.  Mutable pieces: ``live`` (deletes) only."""

    def __init__(self, seg_id: str, n_docs: int):
        self.seg_id = seg_id
        self.n_docs = n_docs
        self.doc_ids: list[str] = []
        self.id_to_local: dict[str, int] = {}
        self.sources: list[bytes] = []
        self.seq_nos = np.zeros(n_docs, dtype=np.int64)
        self.versions = np.ones(n_docs, dtype=np.int64)
        # local -> custom routing value (only docs indexed with one; the
        # reference stores _routing as a stored field)
        self.routings: dict[int, str] = {}
        # completion field -> {(local, input): weight} — per-INPUT
        # suggestion weights (CompletionFieldMapper stores weight per
        # entry in the FST)
        self.completion_weights: dict[str, dict] = {}
        self.postings: dict[str, PostingsField] = {}
        self.numeric_dv: dict[str, NumericDV] = {}
        self.ordinal_dv: dict[str, OrdinalDV] = {}
        self.vector_dv: dict[str, VectorDV] = {}
        self.geo_dv: dict[str, GeoDV] = {}
        self.nested: dict[str, NestedBlock] = {}
        self.live = np.ones(n_docs, dtype=bool)
        self._device: Optional["DeviceSegment"] = None
        # set True when the device-memory budget unstaged this segment
        # (common/device_ledger.py): scored term-bags then score on the
        # host impact tables byte-identically; anything else restages
        # on demand (counted in device.restages)
        self._device_evicted = False
        # ledger-owner attribution, tagged by the owning engine when a
        # searcher is acquired (bench/tests may leave the defaults)
        self.index_name = "-"
        self.shard_id = 0
        # trained ANN structures, lazily built per (field, method) — the
        # segment is immutable so one training pass serves every query
        # (the k-NN plugin trains at graph-build/flush time; ref
        # plugins/SearchPlugin.java:151 SPI)
        self._ann: dict[tuple, object] = {}

    def ann_index(self, field: str, method: dict):
        """Build-or-fetch the trained IVF/IVF-PQ structure for ``field``.

        Keyed by the method signature so a changed mapping retrains; the
        padded cluster-major layout is what the device search kernels
        consume (ops/ivf.py)."""
        from opensearch_tpu.ops.ivf import IvfIndex, IvfPqIndex

        dv = self.vector_dv.get(field)
        if dv is None or not dv.exists.any():
            return None
        name = method.get("name", "ivf")
        # default nlist ~ sqrt(n) (FAISS guidance), clamped to >=1
        nlist = int(method.get("nlist")
                    or max(1, int(np.sqrt(max(int(dv.exists.sum()), 1)))))
        m = int(method.get("m", 8))
        key = (field, name, nlist, m)
        idx = self._ann.get(key)
        if idx is None:
            if name == "ivf_pq":
                idx = IvfPqIndex.build(dv.values, dv.exists, nlist, m=m)
            else:
                idx = IvfIndex.build(dv.values, dv.exists, nlist)
            self._ann[key] = idx
        return idx

    # -- stats used for cross-segment collection statistics ---------------

    def live_count(self) -> int:
        return int(self.live.sum())

    def delete_local(self, local_id: int):
        self.apply_deletes([local_id])

    def apply_deletes(self, local_ids):
        """Copy-on-write: searchers that snapshotted the previous ``live``
        array keep their point-in-time view (Lucene reader semantics)."""
        live = self.live.copy()
        live[np.asarray(local_ids, dtype=np.int64)] = False
        self.live = live

    def source(self, local_id: int) -> dict:
        return json.loads(self.sources[local_id])

    def impact_table(self, field: str, avgdl: float,
                     k1: float = 1.2, b: float = 0.75):
        """Host-side per-posting BM25 impacts + per-term BLOCK-MAX
        metadata for ``field``, as ``(impacts f32 [P], max f32 [T])``.

        ``impacts[p] = tf/(tf + k1*(1-b + b*dl/avgdl))`` — the eager
        BM25S precompute; the float32 operation order matches
        ``ops/bm25.py::compute_impacts`` bit-for-bit so the host and
        device scoring paths produce identical scores.  ``max[t]`` is
        the segment-block maximum per term (the BMW/MaxScore
        upper-bound table of the reference's ``ImpactsEnum``, ref
        org.apache.lucene.index.Impacts), consumed by
        ``plan.max_score_bound`` to skip segments that provably cannot
        beat a min_score / running top-k threshold.

        Keyed by (field, avgdl): a refresh/merge changes the shard
        avgdl through the reader-generation bump, so stale tables stop
        being requested and LRU out."""
        pf = self.postings.get(field)
        if pf is None:
            return None
        from opensearch_tpu.common.cache import attached_cache
        cache = attached_cache(self, "_impact_table_cache",
                               name="segment.impact_table",
                               max_weight=256 << 20, breaker="fielddata")
        key = (field, float(np.float32(avgdl)), k1, b)
        out = cache.get(key)
        if out is None:
            T = len(pf.offsets) - 1
            imp = np.zeros(0, dtype=np.float32)
            mx = np.zeros(T, dtype=np.float32)
            if len(pf.tfs):
                dl = pf.doc_lens[pf.doc_ids]
                norm = np.float32(k1) * (np.float32(1.0 - b)
                                         + np.float32(b) * dl
                                         / np.float32(avgdl))
                imp = (pf.tfs / (pf.tfs + norm)).astype(np.float32)
                lens = np.diff(pf.offsets)
                starts = np.minimum(pf.offsets[:-1], len(imp) - 1)
                mx = np.where(lens > 0,
                              np.maximum.reduceat(imp, starts),
                              np.float32(0.0))
            out = (imp, mx)
            cache.put(key, out)
        return out

    def max_impacts(self, field: str, avgdl: float,
                    k1: float = 1.2, b: float = 0.75):
        """Per-term block-max impacts (see ``impact_table``)."""
        table = self.impact_table(field, avgdl, k1, b)
        return None if table is None else table[1]

    def quantized_table(self, field: str, avgdl: float):
        """Quantized + bit-packed tables for ``field`` at this avgdl
        (``index/codec.py``), host-side and cached like
        ``impact_table``.  When ``self.quant_dir`` is set (the store
        attaches it on load), the persisted ``.quant`` sidecar is tried
        first — a CRC mismatch degrades to recompute-and-rewrite, never
        a failed search — and fresh builds are written back so the next
        process skips the quantization pass."""
        pf = self.postings.get(field)
        if pf is None:
            return None
        from opensearch_tpu.common.cache import attached_cache
        cache = attached_cache(self, "_quant_table_cache",
                               name="segment.quantized_table",
                               max_weight=256 << 20,
                               breaker="fielddata")
        key = (field, float(np.float32(avgdl)))
        qt = cache.get(key)
        if qt is None:
            from opensearch_tpu.index import codec as codec_mod
            qdir = getattr(self, "quant_dir", None)
            if qdir is not None:
                from opensearch_tpu.index import store as store_mod
                try:
                    qt = store_mod.load_quantized_tables(
                        qdir, self.seg_id, field, avgdl=key[1])
                except store_mod.CorruptIndexError:
                    qt = None       # degrade: recompute + rewrite
            if qt is None:
                imp, mx = self.impact_table(field, avgdl)
                qt = codec_mod.quantize_postings(pf, imp, mx, avgdl)
                if qdir is not None:
                    try:
                        store_mod.save_quantized_tables(
                            qdir, self.seg_id, field, qt)
                    except OSError:
                        pass        # sidecar is a cache, not a commit
            qt._offsets = pf.offsets
            cache.put(key, qt)
        return qt

    def device(self) -> "DeviceSegment":
        if self._device is None:
            was_evicted = self._device_evicted
            try:
                self._device = DeviceSegment(self)
            except Exception as exc:
                from opensearch_tpu.common.device_health import (
                    device_health, is_device_error)
                if not is_device_error(exc):
                    raise
                # staging failed (device OOM et al.): the segment is
                # treated as budget-evicted — scored term-bags take the
                # byte-identical host impact-table fallback instead of
                # failing the search; plans that truly need the device
                # degrade via their own dispatch-site handlers
                self._device = None
                self._device_evicted = True
                from opensearch_tpu.common.telemetry import metrics
                metrics().counter("device.restage_failures").inc()
                device_health().record_failure("staging", exc)
                raise
            if was_evicted:
                # demand paging's fault path: a budget-evicted segment
                # was staged again (a plan without a host fallback
                # needed the device arrays back)
                from opensearch_tpu.common.device_ledger import \
                    device_ledger
                device_ledger().record_restage()
                self._device_evicted = False
            from opensearch_tpu.common.device_health import device_health
            device_health().record_success("staging")
        return self._device


class DeviceSegment:
    """jnp-staged view of a Segment, padded to power-of-two shapes.

    Padding scheme: ``n_pad >= n_docs + 1`` so slot ``n_docs`` is a dead
    scatter target for padded postings/value entries; ``live`` is False on
    all padding slots so they can never reach the top-k.
    """

    def __init__(self, seg: Segment):
        import opensearch_tpu.common.jaxenv  # noqa: F401

        self.seg = seg
        self.n_docs = seg.n_docs
        self.n_pad = pad_pow2(seg.n_docs + 1)
        n_pad = self.n_pad
        # HBM budget: the breaker estimate comes from the ONE footprint
        # source of truth (device_ledger.host_footprint; padding roughly
        # doubles worst-case, x2 covers it), charged BEFORE any device
        # allocation — an oversized staging is rejected as 429, not an
        # OOM (FileCache/fielddata-breaker analog)
        from opensearch_tpu.common.breakers import breaker_service
        from opensearch_tpu.common.device_ledger import (device_ledger,
                                                         host_footprint)
        self._breaker_bytes = host_footprint(seg) * 2
        breaker = breaker_service().fielddata
        breaker.add_estimate(self._breaker_bytes,
                             label=f"segment [{seg.seg_id}] staging")
        import weakref
        # idempotent release handle: fires on GC, or EARLY when the
        # device-memory budget unstages this segment (finalize runs once)
        self._breaker_fin = weakref.finalize(self, breaker.release,
                                             self._breaker_bytes)
        # residency ledger: every staged array below is recorded under
        # this group (owner = index/shard/segment); the evict callback
        # is how `device.memory.budget_bytes` unstages us — the Segment
        # flips to its host fallback and the breaker charge releases
        led = self._ledger = device_ledger()
        seg_ref = weakref.ref(seg)
        dseg_ref = weakref.ref(self)

        def _unstage():
            s = seg_ref()
            d = dseg_ref()
            if s is not None and (d is None or s._device is d):
                s._device = None
                s._device_evicted = True
            if d is not None:
                d._breaker_fin()

        group = self._ledger_group = led.open_group(
            index=getattr(seg, "index_name", "-"),
            shard=getattr(seg, "shard_id", 0),
            segment=seg.seg_id, evict=_unstage)
        led.tether(self, group)

        def pad1(a: np.ndarray, size: int, fill) -> np.ndarray:
            out = np.full(size, fill, dtype=a.dtype)
            out[: len(a)] = a
            return out

        def stage(arr, kind, field, name):
            return led.stage(group, arr, kind=kind, field=field,
                             name=name)

        # Lowering decision (index/codec.py): quantized segments stage
        # only offsets/doc_lens/field_exists eagerly — the heavy
        # per-posting columns either flow through the pager in
        # compressed form (scored term-bags) or stage lazily on first
        # demand (``ensure_postings``, for phrase/span/filter plans the
        # quantized kernels don't cover).
        from opensearch_tpu.index import codec as codec_mod
        self.quantized_mode = codec_mod.use_quantized(seg)
        self.postings: dict[str, dict] = {}
        for name, pf in seg.postings.items():
            # offsets padded by repeating the final cumulative value so
            # padded term ids decode as empty ranges and the array shape
            # stays bucketed (compile-cache sharing across segments).
            t_pad = pad_pow2(len(pf.offsets))
            self.postings[name] = {
                "offsets": stage(pad1(pf.offsets, t_pad, pf.offsets[-1]),
                                 "postings", name, "offsets"),
                "doc_lens": stage(pad1(pf.doc_lens, n_pad, 1.0),
                                  "postings", name, "doc_lens"),
                "field_exists": stage(pad1(pf.present, n_pad, False),
                                      "postings", name, "field_exists"),
            }
            if not self.quantized_mode:
                self.ensure_postings(name)
        self.numeric: dict[str, dict] = {}
        for name, dv in seg.numeric_dv.items():
            v_pad = pad_pow2(len(dv.values))
            vals = dv.values
            self.numeric[name] = {
                "values": stage(pad1(vals, v_pad, 0),
                                "numeric", name, "values"),
                "value_docs": stage(
                    pad1(dv.value_docs, v_pad, self.n_docs),
                    "numeric", name, "value_docs"),
                "minv": stage(
                    pad1(dv.minv, n_pad,
                         LONG_MISSING_MAX if dv.kind == "long"
                         else np.inf),
                    "numeric", name, "minv"),
                "maxv": stage(
                    pad1(dv.maxv, n_pad,
                         LONG_MISSING_MIN if dv.kind == "long"
                         else -np.inf),
                    "numeric", name, "maxv"),
                "exists": stage(pad1(dv.exists, n_pad, False),
                                "numeric", name, "exists"),
            }
        self.ordinal: dict[str, dict] = {}
        for name, dv in seg.ordinal_dv.items():
            v_pad = pad_pow2(len(dv.ords))
            self.ordinal[name] = {
                "ords": stage(pad1(dv.ords, v_pad, -1),
                              "ordinal", name, "ords"),
                "value_docs": stage(
                    pad1(dv.value_docs, v_pad, self.n_docs),
                    "ordinal", name, "value_docs"),
                "min_ord": stage(pad1(dv.min_ord, n_pad, -1),
                                 "ordinal", name, "min_ord"),
                "max_ord": stage(pad1(dv.max_ord, n_pad, -1),
                                 "ordinal", name, "max_ord"),
                "exists": stage(pad1(dv.exists, n_pad, False),
                                "ordinal", name, "exists"),
                "n_ords": len(dv.ord_terms),
            }
        self.vector: dict[str, dict] = {}
        for name, dv in seg.vector_dv.items():
            vals = np.zeros((n_pad, dv.dim), dtype=np.float32)
            vals[: len(dv.values)] = dv.values
            self.vector[name] = {
                "values": stage(vals, "vector", name, "values"),
                "exists": stage(pad1(dv.exists, n_pad, False),
                                "vector", name, "exists"),
            }
        self.geo: dict[str, dict] = {}
        for name, dv in seg.geo_dv.items():
            v_pad = pad_pow2(len(dv.lats))
            self.geo[name] = {
                "lats": stage(pad1(dv.lats, v_pad, 0.0),
                              "geo", name, "lats"),
                "lons": stage(pad1(dv.lons, v_pad, 0.0),
                              "geo", name, "lons"),
                "value_docs": stage(
                    pad1(dv.value_docs, v_pad, self.n_docs),
                    "geo", name, "value_docs"),
                "exists": stage(pad1(dv.exists, n_pad, False),
                                "geo", name, "exists"),
            }
        # bounded-cache: one staged copy per live-bitmap version, freed
        self._live_cache: dict[int, object] = {}  # with its PIT searcher
        self._ann_staged: dict[int, tuple] = {}
        self.live = self.live_jnp(seg.live)
        # fully staged: from here on the group is a budget-eviction
        # candidate (lazily staged impacts/live/nested entries keep
        # accruing into it)
        led.seal(group)

    def ensure_postings(self, field: str) -> Optional[dict]:
        """Full per-posting device arrays (doc_ids/tfs/positions) for
        ``field``, staged on demand.

        On quantized segments these are skipped at construction — that
        skip IS the footprint win — but plans outside the quantized
        lowering (phrase, span, filter-context term bags, the batched
        union kernel) still need them; they stage here on first use and
        join the segment's ledger group like any eager array."""
        p = self.postings.get(field)
        if p is None or "doc_ids" in p:
            return p
        pf = self.seg.postings[field]
        p_pad = pad_pow2(len(pf.doc_ids))
        pos_pad = pad_pow2(len(pf.positions))
        led = self._ledger
        group = self._ledger_group

        def pad1(a: np.ndarray, size: int, fill) -> np.ndarray:
            out = np.full(size, fill, dtype=a.dtype)
            out[: len(a)] = a
            return out

        def stage(arr, name):
            return led.stage(group, arr, kind="postings", field=field,
                             name=name)

        p["doc_ids"] = stage(pad1(pf.doc_ids, p_pad, self.n_docs),
                             "doc_ids")
        p["tfs"] = stage(pad1(pf.tfs, p_pad, 0.0), "tfs")
        # positions CSR for phrase matching (pos_offsets is per posting
        # entry, so a term's positions are one contiguous slice of
        # ``positions``).
        p["pos_offsets"] = stage(
            pad1(pf.pos_offsets, pad_pow2(len(pf.pos_offsets)),
                 pf.pos_offsets[-1] if len(pf.pos_offsets) else 0),
            "pos_offsets")
        p["positions"] = stage(pad1(pf.positions, pos_pad, 0),
                               "positions")
        if self.quantized_mode:
            # diagnostic: how often the compressed layout had to pull
            # the full f32 arrays in anyway (plan mix dependent)
            from opensearch_tpu.common.telemetry import metrics
            metrics().counter("device.quantized.full_postings").inc()
        return p

    def quantized(self, field: str, avgdl: float):
        """Quantized device arrays for ``field`` (index/codec.py),
        staged through the device pager under the page budget.

        Returns the staged dict (qvals/scales/exact_vals/exact_offsets/
        packed/base) or None if the field has no postings.  Pager
        entries are keyed by (index, shard, segment, field, avgdl) and
        deliberately OUTLIVE this DeviceSegment: a budget eviction of
        the segment group doesn't drop the compressed pages, so the
        restage path only re-stages the cheap eager arrays."""
        if self.postings.get(field) is None:
            return None
        seg = self.seg
        key = _quant_key(seg, field, avgdl)
        from opensearch_tpu.common.device_ledger import device_pager
        _register_pager_invalidation(seg, key)
        return device_pager().acquire(
            key, lambda: _quant_items(seg, field, avgdl),
            index=getattr(seg, "index_name", "-"),
            shard=getattr(seg, "shard_id", 0),
            segment=seg.seg_id)

    def impacts(self, field: str, avgdl: float):
        """Staged per-posting BM25 impact column for ``field``, indexed
        exactly like ``postings[field]["tfs"]`` (padded slots are 0).

        Staged from the HOST impact table (``Segment.impact_table``) so
        the device scoring path and the CPU-backend host fast path read
        bit-identical impacts, and cached per (field, avgdl).  avgdl is
        the only query-time input: a refresh/merge that changes it does
        so through the reader-generation bump (new searcher, new
        ShardContext stats), so the old keys stop being requested and
        LRU out — staleness is structurally impossible."""
        p = self.postings.get(field)
        from opensearch_tpu.common.cache import attached_cache
        cache = attached_cache(self, "_impact_cache",
                               name="segment.impacts",
                               max_weight=256 << 20, breaker="fielddata")
        key = (field, float(np.float32(avgdl)))
        imp = cache.get(key)
        if imp is None:
            import jax.numpy as jnp
            if p is None:
                imp = jnp.zeros(8, jnp.float32)
            else:
                host_imp, _mx = self.seg.impact_table(field, avgdl)
                # padded like doc_ids/tfs even when those are lazily
                # staged (quantized segments): same bucketed shape
                p_pad = pad_pow2(len(self.seg.postings[field].doc_ids))
                padded = np.zeros(p_pad, np.float32)
                padded[: len(host_imp)] = host_imp
                imp = self._ledger.stage(       # quantize-ok
                    self._ledger_group, padded, kind="impacts",
                    field=field, name=f"avgdl={key[1]:.6g}")
            cache.put(key, imp)
        return imp

    def nested_staged(self, path: str) -> Optional[dict]:
        """Padded device arrays for one nested block (lazy, cached)."""
        cache = getattr(self, "_nested_cache", None)
        if cache is None:
            # bounded-cache: at most one entry per nested mapping path
            cache = self._nested_cache = {}
        if path in cache:
            return cache[path]
        block = self.seg.nested.get(path)
        if block is None or block.n_objs == 0:
            cache[path] = None
            return None

        def pad1(a, size, fill, name=""):
            out = np.full(size, fill, dtype=a.dtype)
            out[: len(a)] = a
            return self._ledger.stage(self._ledger_group, out,
                                      kind="nested", field=path,
                                      name=name)

        n_obj_pad = pad_pow2(block.n_objs + 1)
        staged = {
            "n_obj_pad": n_obj_pad,
            # padding objects belong to the parent dead slot
            "obj_to_doc": pad1(block.obj_to_doc, n_obj_pad,
                               self.n_pad - 1, "obj_to_doc"),
            "obj_valid": pad1(np.ones(block.n_objs, bool), n_obj_pad,
                              False, "obj_valid"),
            "numeric": {}, "ordinal": {},
        }
        for f, (values, value_objs) in block.numeric.items():
            v_pad = pad_pow2(len(values))
            staged["numeric"][f] = {
                "values": pad1(values, v_pad, 0.0, f"{f}/values"),
                "value_objs": pad1(value_objs, v_pad, n_obj_pad - 1,
                                   f"{f}/value_objs"),
                "v_pad": v_pad,
            }
        for f, (ord_terms, ords, value_objs) in block.ordinal.items():
            v_pad = pad_pow2(len(ords))
            staged["ordinal"][f] = {
                "ords": pad1(ords, v_pad, -1, f"{f}/ords"),
                "value_objs": pad1(value_objs, v_pad, n_obj_pad - 1,
                                   f"{f}/value_objs"),
                "v_pad": v_pad,
            }
        cache[path] = staged
        return staged

    def ann_staged(self, idx) -> tuple:
        """Device-staged arrays for a trained ANN index (strong-keyed by
        the host object so a retrain restages)."""
        key = id(idx)
        cached = self._ann_staged.get(key)
        if cached is None or cached[0] is not idx:
            cached = (idx, idx.device())
            if len(self._ann_staged) >= 4:
                old = next(iter(self._ann_staged))
                self._ann_staged.pop(old)
                self._ledger.drop(self._ledger_group, kind="ann",
                                  name=str(old))
            self._ann_staged[key] = cached
            # ANN builders stage their own arrays (ops/ivf.py); the
            # ledger adopts the accounting so residency stays exact
            self._ledger.adopt(self._ledger_group, cached[1],
                               kind="ann", name=str(key))
        return cached[1]

    def live_jnp(self, live_np: np.ndarray):
        """Staged live mask for a SNAPSHOT of the live bitmap (keyed by
        array identity — apply_deletes replaces the array, so old
        snapshots keep resolving to their own staged copy).  The cache
        holds a strong reference to the keyed numpy array: id() keys are
        only valid while the object is alive."""
        key = id(live_np)
        cached = self._live_cache.get(key)
        if cached is None or cached[0] is not live_np:
            padded = np.zeros(self.n_pad, dtype=bool)
            padded[: len(live_np)] = live_np
            cached = (live_np,
                      self._ledger.stage(self._ledger_group, padded,
                                         kind="live", name=str(key)))
            if len(self._live_cache) >= 4:
                old = next(iter(self._live_cache))
                self._live_cache.pop(old)
                self._ledger.drop(self._ledger_group, kind="live",
                                  name=str(old))
            self._live_cache[key] = cached
        return cached[1]


def _quant_key(seg: Segment, field: str, avgdl: float) -> tuple:
    """Pager key for one quantized table set — stable across
    DeviceSegment restages so compressed pages survive segment-group
    eviction."""
    return (getattr(seg, "index_name", "-"),
            getattr(seg, "shard_id", 0),
            seg.seg_id, field, float(np.float32(avgdl)))


def _quant_items(seg: Segment, field: str, avgdl: float) -> list:
    """Pager loader: one quantized table set as padded host arrays,
    shape-bucketed exactly like the eager staging so XLA programs are
    shared across same-bucket segments."""
    qt = seg.quantized_table(field, avgdl)
    pf = seg.postings[field]
    t_pad = pad_pow2(len(pf.offsets))

    def pad1(a: np.ndarray, size: int, fill) -> np.ndarray:
        out = np.full(size, fill, dtype=a.dtype)
        out[: len(a)] = a
        return out

    return [
        ("qvals", "impacts_q",
         pad1(qt.qvals, pad_pow2(len(qt.qvals)), 0)),
        # padded term slots are inactive in every gather; scale 1 keeps
        # a stray read finite
        ("scales", "impacts_q", pad1(qt.scales, t_pad, 1.0)),
        ("exact_vals", "impacts_q",
         pad1(qt.exact_vals, pad_pow2(len(qt.exact_vals)), 0.0)),
        ("exact_offsets", "impacts_q",
         pad1(qt.exact_offsets, t_pad,
              qt.exact_offsets[-1] if len(qt.exact_offsets) else 0)),
        # packed keeps its own guard word; zero padding beyond it is
        # never addressed (w+1 <= word count of the real payload)
        ("packed", "postings_q",
         pad1(qt.packed, pad_pow2(len(qt.packed)), 0)),
        ("base", "postings_q", pad1(qt.base, t_pad, 0)),
    ]


def _pager_invalidate(key: tuple) -> None:
    from opensearch_tpu.common.device_ledger import device_pager
    device_pager().invalidate(key)


def _register_pager_invalidation(seg: Segment, key: tuple) -> None:
    """One finalizer per (segment, pager key): a merged-away/GC'd
    segment drops its compressed pages instead of squatting in the
    budget until LRU."""
    import weakref
    reg = getattr(seg, "_quant_pager_keys", None)
    if reg is None:
        reg = seg._quant_pager_keys = set()
    if key not in reg:
        reg.add(key)
        weakref.finalize(seg, _pager_invalidate, key)


def prefetch_quantized(seg: Segment, field: str, avgdl: float) -> bool:
    """Prefetch-oracle entry point: stage a segment's quantized tables
    into FREE pager pages ahead of the dispatch loop (never evicts —
    see ``DevicePager.prefetch``).  The footprint hint is an estimate
    so a skipped prefetch costs no quantization work."""
    pf = seg.postings.get(field)
    if pf is None:
        return False
    key = _quant_key(seg, field, avgdl)
    # ~1B/posting quantized impacts + <=4B/posting packed ids + per-term
    # scale/base/offset columns; close enough for page-granular fit
    hint = (len(pf.doc_ids) * 5
            + len(pf.offsets) * 12 + 4096)
    from opensearch_tpu.common.device_ledger import device_pager
    _register_pager_invalidation(seg, key)
    return device_pager().prefetch(
        key, lambda: _quant_items(seg, field, avgdl), hint,
        index=getattr(seg, "index_name", "-"),
        shard=getattr(seg, "shard_id", 0),
        segment=seg.seg_id)


class SegmentWriter:
    """Builds an immutable Segment from a batch of ParsedDocuments — the
    invert step Lucene does inside IndexWriter.addDocuments (ref
    index/engine/InternalEngine.java:1186), done columnar in one pass."""

    def build(self, docs: list[ParsedDocument], seg_id: str,
              norms_fields: Optional[dict[str, bool]] = None,
              vector_meta: Optional[dict[str, dict]] = None) -> Segment:
        n = len(docs)
        seg = Segment(seg_id, n)
        norms_fields = norms_fields or {}
        vector_meta = vector_meta or {}

        # term -> list index accumulation per field
        inv: dict[str, dict[str, list[tuple[int, int, list[int]]]]] = {}
        field_doc_lens: dict[str, np.ndarray] = {}
        longs: dict[str, list[list[int]]] = {}
        doubles: dict[str, list[list[float]]] = {}
        ordinals: dict[str, list[list[str]]] = {}
        vectors: dict[str, dict[int, list[float]]] = {}
        geos: dict[str, list[list[tuple[float, float]]]] = {}

        for i, doc in enumerate(docs):
            seg.doc_ids.append(doc.doc_id)
            seg.id_to_local[doc.doc_id] = i
            seg.sources.append(json.dumps(doc.source, separators=(",", ":")).encode())
            seg.seq_nos[i] = doc.seq_no
            seg.versions[i] = doc.version
            if doc.routing is not None:
                seg.routings[i] = doc.routing
            for cfield, entries in doc.completions.items():
                wmap = seg.completion_weights.setdefault(cfield, {})
                for text, weight in entries:
                    key = (i, text)
                    # an explicit weight of 0 must round-trip (it ranks
                    # LAST, not as the implicit 1)
                    if key not in wmap or weight > wmap[key]:
                        wmap[key] = weight
            for fname, toks in doc.tokens.items():
                per_term: dict[str, tuple[int, list[int]]] = {}
                for term, pos in toks:
                    if term in per_term:
                        tf, plist = per_term[term]
                        per_term[term] = (tf + 1, plist)
                        plist.append(pos)
                    else:
                        per_term[term] = (1, [pos])
                finv = inv.setdefault(fname, {})
                for term, (tf, plist) in per_term.items():
                    finv.setdefault(term, []).append((i, tf, plist))
            for fname, length in doc.field_lengths.items():
                arr = field_doc_lens.setdefault(fname, np.zeros(n, dtype=np.float32))
                arr[i] = length
            for fname, vals in doc.longs.items():
                longs.setdefault(fname, [[] for _ in range(n)])[i].extend(vals)
            for fname, vals in doc.doubles.items():
                doubles.setdefault(fname, [[] for _ in range(n)])[i].extend(vals)
            for fname, vals in doc.ordinals.items():
                ordinals.setdefault(fname, [[] for _ in range(n)])[i].extend(vals)
            for fname, vec in doc.vectors.items():
                vectors.setdefault(fname, {})[i] = vec
            for fname, pts in doc.geo_points.items():
                geos.setdefault(fname, [[] for _ in range(n)])[i].extend(pts)

        field_present: dict[str, np.ndarray] = {}
        for i, doc in enumerate(docs):
            for fname in doc.field_lengths:
                field_present.setdefault(
                    fname, np.zeros(n, dtype=bool))[i] = True

        for fname in set(inv) | set(field_present):
            seg.postings[fname] = self._build_postings(
                fname, inv.get(fname, {}), n, field_doc_lens.get(fname),
                has_norms=norms_fields.get(fname, fname in field_doc_lens),
                present=field_present.get(fname))

        for fname, per_doc in longs.items():
            seg.numeric_dv[fname] = self._build_numeric(per_doc, n, "long")
        for fname, per_doc in doubles.items():
            seg.numeric_dv[fname] = self._build_numeric(per_doc, n, "double")
        for fname, per_doc in ordinals.items():
            seg.ordinal_dv[fname] = self._build_ordinal(per_doc, n)
        for fname, per_doc in vectors.items():
            meta = vector_meta.get(fname, {})
            dim = meta.get("dims") or len(next(iter(per_doc.values())))
            vals = np.zeros((n, dim), dtype=np.float32)
            exists = np.zeros(n, dtype=bool)
            for i, vec in per_doc.items():
                vals[i] = np.asarray(vec, dtype=np.float32)
                exists[i] = True
            seg.vector_dv[fname] = VectorDV(
                values=vals, exists=exists, dim=dim,
                similarity=meta.get("similarity", "l2_norm"))
        for fname, per_doc in geos.items():
            seg.geo_dv[fname] = self._build_geo(per_doc, n)
        self._build_nested(docs, seg)
        return seg

    @staticmethod
    def _build_nested(docs: list[ParsedDocument], seg: Segment):
        """Object-major nested blocks: objects append in doc order, child
        columns key by object id (see NestedBlock)."""
        paths = sorted({p for d in docs for p in d.nested})
        for path in paths:
            obj_to_doc: list[int] = []
            num_cols: dict[str, tuple[list, list]] = {}
            ord_raw: dict[str, tuple[list, list]] = {}   # terms, objs
            for i, doc in enumerate(docs):
                for obj in doc.nested.get(path, []):
                    oid = len(obj_to_doc)
                    obj_to_doc.append(i)
                    for child, (kind, values) in obj.items():
                        if kind == "num":
                            vals, objs = num_cols.setdefault(child,
                                                             ([], []))
                        else:
                            vals, objs = ord_raw.setdefault(child,
                                                            ([], []))
                        for v in values:
                            vals.append(v)
                            objs.append(oid)
            if not obj_to_doc:
                continue
            block = NestedBlock(
                obj_to_doc=np.asarray(obj_to_doc, np.int32))
            for child, (vals, objs) in num_cols.items():
                block.numeric[child] = (
                    np.asarray(vals, np.float64),
                    np.asarray(objs, np.int32))
            for child, (terms, objs) in ord_raw.items():
                ord_terms = sorted(set(terms))
                term_to_ord = {t: o for o, t in enumerate(ord_terms)}
                block.ordinal[child] = (
                    ord_terms,
                    np.asarray([term_to_ord[t] for t in terms],
                               np.int32),
                    np.asarray(objs, np.int32))
            seg.nested[path] = block

    @staticmethod
    def _build_postings(fname, finv, n_docs, doc_lens, has_norms,
                        present=None) -> PostingsField:
        terms_sorted = sorted(finv)
        term_ids = {t: i for i, t in enumerate(terms_sorted)}
        T = len(terms_sorted)
        df = np.zeros(T, dtype=np.int32)
        offsets = np.zeros(T + 1, dtype=np.int32)
        has_terms = np.zeros(n_docs, dtype=bool)
        doc_list, tf_list, pos_off, pos_all = [], [], [0], []
        for t_idx, term in enumerate(terms_sorted):
            entries = finv[term]  # already ascending doc id (insert order)
            df[t_idx] = len(entries)
            for d, tf, plist in entries:
                doc_list.append(d)
                tf_list.append(tf)
                pos_all.extend(plist)
                pos_off.append(len(pos_all))
                has_terms[d] = True
            offsets[t_idx + 1] = len(doc_list)
        if doc_lens is None:
            doc_lens = np.ones(n_docs, dtype=np.float32)
        docs_with = int((doc_lens > 0).sum()) if has_norms else n_docs
        if not has_norms:
            doc_lens = np.ones(n_docs, dtype=np.float32)
        if present is None:
            present = has_terms
        return PostingsField(
            terms=term_ids, df=df, offsets=offsets,
            doc_ids=np.asarray(doc_list, dtype=np.int32),
            tfs=np.asarray(tf_list, dtype=np.float32),
            pos_offsets=np.asarray(pos_off, dtype=np.int32),
            positions=np.asarray(pos_all, dtype=np.int32),
            doc_lens=doc_lens.astype(np.float32),
            total_len=float(doc_lens[doc_lens > 0].sum()) if has_norms else float(n_docs),
            docs_with_field=docs_with, has_norms=has_norms,
            present=present)

    @staticmethod
    def _build_numeric(per_doc: list[list], n_docs: int, kind: str) -> NumericDV:
        dtype = np.int64 if kind == "long" else np.float64
        miss_min = LONG_MISSING_MAX if kind == "long" else np.inf
        miss_max = LONG_MISSING_MIN if kind == "long" else -np.inf
        offsets = np.zeros(n_docs + 1, dtype=np.int32)
        values, value_docs = [], []
        minv = np.full(n_docs, miss_min, dtype=dtype)
        maxv = np.full(n_docs, miss_max, dtype=dtype)
        exists = np.zeros(n_docs, dtype=bool)
        for i, vals in enumerate(per_doc):
            vals = sorted(vals)
            values.extend(vals)
            value_docs.extend([i] * len(vals))
            offsets[i + 1] = len(values)
            if vals:
                minv[i], maxv[i] = vals[0], vals[-1]
                exists[i] = True
        return NumericDV(kind=kind, offsets=offsets,
                         values=np.asarray(values, dtype=dtype),
                         value_docs=np.asarray(value_docs, dtype=np.int32),
                         minv=minv, maxv=maxv, exists=exists)

    @staticmethod
    def _build_ordinal(per_doc: list[list[str]], n_docs: int) -> OrdinalDV:
        uniq = sorted({t for vals in per_doc for t in vals})
        term_to_ord = {t: i for i, t in enumerate(uniq)}
        offsets = np.zeros(n_docs + 1, dtype=np.int32)
        ords, value_docs = [], []
        min_ord = np.full(n_docs, -1, dtype=np.int32)
        max_ord = np.full(n_docs, -1, dtype=np.int32)
        exists = np.zeros(n_docs, dtype=bool)
        for i, vals in enumerate(per_doc):
            # SortedSetDocValues semantics: per-doc ordinals are DEDUPED
            # (unlike SortedNumeric, which keeps duplicate values)
            o = sorted({term_to_ord[t] for t in vals})
            ords.extend(o)
            value_docs.extend([i] * len(o))
            offsets[i + 1] = len(ords)
            if o:
                min_ord[i], max_ord[i] = o[0], o[-1]
                exists[i] = True
        return OrdinalDV(ord_terms=uniq, term_to_ord=term_to_ord,
                         offsets=offsets,
                         ords=np.asarray(ords, dtype=np.int32),
                         value_docs=np.asarray(value_docs, dtype=np.int32),
                         min_ord=min_ord, max_ord=max_ord, exists=exists)

    @staticmethod
    def _build_geo(per_doc, n_docs) -> GeoDV:
        offsets = np.zeros(n_docs + 1, dtype=np.int32)
        lats, lons, value_docs = [], [], []
        exists = np.zeros(n_docs, dtype=bool)
        for i, pts in enumerate(per_doc):
            for lat, lon in pts:
                lats.append(lat)
                lons.append(lon)
                value_docs.append(i)
            offsets[i + 1] = len(lats)
            exists[i] = bool(pts)
        return GeoDV(offsets=offsets,
                     lats=np.asarray(lats, dtype=np.float32),
                     lons=np.asarray(lons, dtype=np.float32),
                     value_docs=np.asarray(value_docs, dtype=np.int32),
                     exists=exists)
