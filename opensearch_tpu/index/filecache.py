"""Node-level LRU blob cache backing searchable snapshots.

The reference mounts snapshots as ``remote_snapshot`` indices whose data
stays in the repository, pulled through a bounded on-disk cache (ref
server/src/main/java/org/opensearch/index/store/remote/filecache/
FileCache.java:47, ref server/src/main/java/org/opensearch/node/
Node.java fileCache wiring).  Here the unit is a whole segment file
(content-addressed blob): segments are staged fully into host/device
memory at engine open, so an evicted file is only re-fetched at the next
shard open — eviction never breaks a live searcher.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict


class _InFlight:
    """One pending fetch: waiters park on ``ev``; a failed fetch leaves
    its exception in ``error`` so every waiter re-raises it instead of
    silently turning into a fresh fetcher (a dead repository would
    otherwise stampede: N waiters -> N sequential failing fetches)."""

    __slots__ = ("ev", "error")

    def __init__(self):
        self.ev = threading.Event()
        self.error = None


class FileCache:
    """Bounded content-addressed file cache with LRU eviction.

    ``get(sha, fetch)`` returns a stable path ``<dir>/<sha>`` — stable so
    shard directories can hold symlinks that survive evict/refetch
    cycles.  Fetches run OUTSIDE the cache lock (a slow repository must
    not stall other cache users or stats reads); concurrent misses on
    the same sha dedup via per-sha in-flight events.
    """

    def __init__(self, cache_dir: str, max_bytes: int = 256 << 20):
        self.cache_dir = cache_dir
        self.max_bytes = int(max_bytes)
        os.makedirs(cache_dir, exist_ok=True)
        self._lock = threading.RLock()
        self._entries: "OrderedDict[str, int]" = OrderedDict()  # sha->bytes
        self._in_flight: dict[str, _InFlight] = {}
        # sha -> pin count; pinned blobs are never evicted (mount in
        # progress); counted so nested/overlapping pins compose
        self._pinned: dict[str, int] = {}
        self.hits = self.misses = self.evictions = 0
        for name in sorted(os.listdir(cache_dir)):      # warm restart
            p = os.path.join(cache_dir, name)
            # staging files are named <sha>.tmp.<thread-id> — match the
            # marker anywhere, not just as a suffix, or a crashed
            # fetch's leftover gets indexed as a (corrupt) cache entry
            if os.path.isfile(p) and ".tmp" not in name:
                self._entries[name] = os.path.getsize(p)

    def path(self, sha: str) -> str:
        return os.path.join(self.cache_dir, sha)

    def get(self, sha: str, fetch) -> str:
        """Return the cached path for ``sha``, fetching via ``fetch()``
        (-> bytes) on miss and evicting least-recently-used unpinned
        entries past the budget.  Pinned entries and the just-fetched one
        are never evicted, so a working set larger than the budget still
        materializes (over budget, like the reference's cache under an
        oversized mount)."""
        while True:
            with self._lock:
                if sha in self._entries and os.path.exists(self.path(sha)):
                    self._entries.move_to_end(sha)
                    self.hits += 1
                    return self.path(sha)
                inf = self._in_flight.get(sha)
                if inf is None:
                    self._in_flight[sha] = _InFlight()
                    self.misses += 1
                    break               # this thread fetches
            inf.ev.wait()               # another thread is fetching it
            if inf.error is not None:
                # the fetch this thread deduped onto failed: propagate
                # the SAME error to every waiter (never hang, never
                # stampede the repository with N retries)
                raise inf.error
        inf = self._in_flight[sha]
        try:
            data = fetch()
            tmp = self.path(sha) + ".tmp." + str(threading.get_ident())
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path(sha))
            with self._lock:
                self._entries.pop(sha, None)
                self._entries[sha] = len(data)
                self._evict(keep=sha)
            return self.path(sha)
        except BaseException as e:
            inf.error = e
            raise
        finally:
            with self._lock:
                self._in_flight.pop(sha, None)
            inf.ev.set()

    def invalidate(self, sha: str) -> None:
        """Drop a cached blob (its bytes failed post-fetch verification):
        the next ``get`` re-fetches from the repository."""
        with self._lock:
            self._entries.pop(sha, None)
            try:
                os.remove(self.path(sha))
            except OSError:
                pass

    def pin(self, shas):
        """Context manager: keep ``shas`` out of eviction while a mount
        materializes (the whole file set must coexist until the engines
        have loaded it).  Refcounted, so overlapping pins compose."""
        cache = self
        shas = set(shas)

        class _Pin:
            def __enter__(self):
                with cache._lock:
                    for s in shas:
                        cache._pinned[s] = cache._pinned.get(s, 0) + 1

            def __exit__(self, *exc):
                with cache._lock:
                    for s in shas:
                        n = cache._pinned.get(s, 0) - 1
                        if n <= 0:
                            cache._pinned.pop(s, None)
                        else:
                            cache._pinned[s] = n
                    cache._evict(keep=None)

        return _Pin()

    def set_max_bytes(self, v: int):
        """Dynamic resize; shrinking reclaims disk immediately rather
        than waiting for the next miss."""
        with self._lock:
            self.max_bytes = int(v)
            self._evict(keep=None)

    def _evict(self, keep):
        # caller holds the lock
        total = sum(self._entries.values())
        for victim in list(self._entries):
            if total <= self.max_bytes:
                break
            if victim == keep or victim in self._pinned:
                continue
            total -= self._entries.pop(victim)
            self.evictions += 1
            try:
                os.remove(self.path(victim))
            except OSError:
                pass

    def materialize_shard(self, shard_dir: str, repo):
        """Link a mounted shard's segment files (listed in its
        ``remote_ref.json``) to cached blobs, fetching any the LRU
        evicted.  The shard's whole blob set is pinned for the duration
        so fetching file N can't evict file 1 before the engine opens.
        Symlink targets are the stable cache paths, so an existing link
        whose blob was evicted heals by re-fetching."""
        import json

        ref_path = os.path.join(shard_dir, "remote_ref.json")
        with open(ref_path) as f:
            ref = json.load(f)
        seg_dir = os.path.join(shard_dir, "segments")
        os.makedirs(seg_dir, exist_ok=True)
        with self.pin({fm["blob"] for fm in ref["files"]}):
            for fmeta in ref["files"]:
                from opensearch_tpu.index.remote_store import (
                    validate_manifest_name)
                validate_manifest_name(fmeta["name"])
                blob = fmeta["blob"]
                target = self.get(
                    blob, lambda b=blob: repo.blobs.read_blob(b))
                link = os.path.join(seg_dir, fmeta["name"])
                if os.path.islink(link) or os.path.exists(link):
                    os.remove(link)
                os.symlink(target, link)

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries),
                    "size_in_bytes": sum(self._entries.values()),
                    "max_size_in_bytes": self.max_bytes,
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions,
                    # mount/refill pressure: bytes pinned against
                    # eviction and fetches currently in flight
                    "pinned_entries": len(self._pinned),
                    "pinned_bytes": sum(
                        self._entries.get(s, 0) for s in self._pinned),
                    "in_flight": len(self._in_flight)}
