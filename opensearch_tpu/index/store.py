"""On-disk segment format.

Analog of the Lucene codec + ``index/store/Store.java``: one ``.npz`` of
flat arrays + one ``.json`` of dictionaries/metadata + one ``.src`` blob of
concatenated _source bytes per segment.  Arrays are written exactly as the
in-memory Segment holds them (the device staging re-pads on load), and the
live-docs bitmap is rewritten in place on delete-commit like Lucene's
``.liv`` files.

Durability + integrity (the ``CodecUtil.checkFooter`` / ``Store.verify``
analogs): every segment commit writes its data files tmp+fsync+rename and
then commits them with ONE atomic rename of a ``<seg_id>.manifest`` file
recording the length and CRC32 of every data file — a crash anywhere in
the sequence leaves either no manifest (the segment never existed) or a
manifest whose files all verify.  ``load_segment`` / ``verify_segment``
check every byte against the manifest before decoding and raise
``CorruptIndexError`` naming the offending file; the ``.liv`` sidecar
(rewritten on delete-commit, so it can't live in the immutable manifest)
carries its own CRC32 footer-style header instead.  A detected corruption
is recorded as a ``corrupted_<seg_id>.json`` marker in the segment
directory (``Store.markStoreCorrupted`` / ``CorruptedFileException``) and
a marked store refuses to open until the copy is dropped and re-recovered.
"""

from __future__ import annotations

import io
import json
import os
import zlib

import numpy as np

from opensearch_tpu.common.errors import OpenSearchTpuError
from opensearch_tpu.index.segment import (
    GeoDV,
    NumericDV,
    OrdinalDV,
    PostingsField,
    Segment,
    VectorDV,
)


class CorruptIndexError(OpenSearchTpuError):
    status = 500


def _segment_encode(seg: Segment):
    """Split a Segment into (arrays, meta, src_bytes) — shared by the
    on-disk writer and the wire serializer (segment replication file copy,
    ref indices/replication/SegmentReplicationTargetService.java:208)."""
    arrays: dict[str, np.ndarray] = {
        "seq_nos": seg.seq_nos, "versions": seg.versions, "live": seg.live,
    }
    meta = {"seg_id": seg.seg_id, "n_docs": seg.n_docs,
            "doc_ids": seg.doc_ids,
            "routings": {str(k): v for k, v in seg.routings.items()},
            "completion_weights": {
                f: {f"{local}\x00{text}": w
                    for (local, text), w in wmap.items()}
                for f, wmap in seg.completion_weights.items()},
            "postings": {}, "numeric": {}, "ordinal": {}, "vector": {},
            "geo": {}, "nested": {}}

    src_offsets = np.zeros(len(seg.sources) + 1, dtype=np.int64)
    for i, b in enumerate(seg.sources):
        src_offsets[i + 1] = src_offsets[i] + len(b)
    arrays["src_offsets"] = src_offsets

    for f, pf in seg.postings.items():
        meta["postings"][f] = {
            "terms": list(pf.terms), "total_len": pf.total_len,
            "docs_with_field": pf.docs_with_field, "has_norms": pf.has_norms,
        }
        for k in ("df", "offsets", "doc_ids", "tfs", "pos_offsets",
                  "positions", "doc_lens", "present"):
            arrays[f"p|{f}|{k}"] = getattr(pf, k)
    for f, dv in seg.numeric_dv.items():
        meta["numeric"][f] = {"kind": dv.kind}
        for k in ("offsets", "values", "value_docs", "minv", "maxv", "exists"):
            arrays[f"n|{f}|{k}"] = getattr(dv, k)
    for f, dv in seg.ordinal_dv.items():
        meta["ordinal"][f] = {"ord_terms": dv.ord_terms}
        for k in ("offsets", "ords", "value_docs", "min_ord", "max_ord",
                  "exists"):
            arrays[f"o|{f}|{k}"] = getattr(dv, k)
    for f, dv in seg.vector_dv.items():
        meta["vector"][f] = {"dim": dv.dim, "similarity": dv.similarity}
        arrays[f"v|{f}|values"] = dv.values
        arrays[f"v|{f}|exists"] = dv.exists
    for f, dv in seg.geo_dv.items():
        meta["geo"][f] = {}
        for k in ("offsets", "lats", "lons", "value_docs", "exists"):
            arrays[f"g|{f}|{k}"] = getattr(dv, k)
    for path, block in seg.nested.items():
        meta["nested"][path] = {
            "numeric_fields": sorted(block.numeric),
            "ordinal_fields": sorted(block.ordinal),
            "ord_terms": {f: block.ordinal[f][0] for f in block.ordinal},
        }
        arrays[f"x|{path}|obj_to_doc"] = block.obj_to_doc
        for f, (values, value_objs) in block.numeric.items():
            arrays[f"x|{path}|n|{f}|values"] = values
            arrays[f"x|{path}|n|{f}|objs"] = value_objs
        for f, (_terms, ords, value_objs) in block.ordinal.items():
            arrays[f"x|{path}|o|{f}|ords"] = ords
            arrays[f"x|{path}|o|{f}|objs"] = value_objs
    return arrays, meta, b"".join(seg.sources)


CODECS = ("default", "best_compression")

MANIFEST_SUFFIX = ".manifest"
_DATA_SUFFIXES = (".json", ".npz", ".src")


def file_checksum(data: bytes) -> dict:
    """The per-file integrity record the manifest carries (CodecUtil
    footer analog: length + CRC32 over the whole payload)."""
    return {"length": len(data), "crc32": zlib.crc32(data) & 0xFFFFFFFF}


def write_durable(path: str, data: bytes):
    """tmp + fsync + atomic rename — the only sanctioned way a file
    reaches its final name in the segment store."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def write_segment_manifest(dirpath: str, seg_id: str, entries: dict):
    """Commit point of a segment: one atomic rename installing the
    manifest that names every data file with its length + CRC32."""
    payload = json.dumps({"seg_id": seg_id, "files": entries},
                         sort_keys=True).encode()
    write_durable(os.path.join(dirpath, seg_id + MANIFEST_SUFFIX), payload)


def read_segment_manifest(dirpath: str, seg_id: str):
    p = os.path.join(dirpath, seg_id + MANIFEST_SUFFIX)
    if not os.path.exists(p):
        return None     # pre-manifest directory (legacy, unverifiable)
    try:
        with open(p, "rb") as f:
            m = json.loads(f.read().decode())
        if not isinstance(m.get("files"), dict):
            raise ValueError("manifest has no [files] map")
        return m
    except (OSError, ValueError) as e:
        raise CorruptIndexError(
            f"segment manifest [{seg_id}{MANIFEST_SUFFIX}] is unreadable: "
            f"{e}") from e


def _verify_bytes(name: str, data: bytes, want: dict):
    got = file_checksum(data)
    if got["length"] != int(want["length"]):
        raise CorruptIndexError(
            f"segment file [{name}] length mismatch: manifest records "
            f"{want['length']} bytes, found {got['length']}")
    if got["crc32"] != int(want["crc32"]):
        raise CorruptIndexError(
            f"segment file [{name}] checksum mismatch: manifest records "
            f"crc32 [{want['crc32']:08x}], found [{got['crc32']:08x}]")


def _read_verified(dirpath: str, name: str, manifest) -> bytes:
    path = os.path.join(dirpath, name)
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as e:
        raise CorruptIndexError(
            f"cannot read segment file [{name}]: {e}") from e
    if manifest is not None:
        want = manifest["files"].get(name)
        if want is None:
            raise CorruptIndexError(
                f"segment file [{name}] is not recorded in its manifest")
        _verify_bytes(name, data, want)
    return data


def _encode_liv(live: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, live)
    payload = buf.getvalue()
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    return f"{crc:08x}".encode() + payload


def _decode_liv(seg_id: str, data: bytes) -> np.ndarray:
    """The .liv sidecar is rewritten on every delete-commit, so it lives
    OUTSIDE the immutable manifest and carries its own CRC32 header
    (8 hex bytes) — legacy raw ``np.save`` payloads (starting with the
    numpy magic, never valid hex) load unverified."""
    head = data[:8]
    try:
        expected = int(head, 16)
    except ValueError:
        return np.load(io.BytesIO(data)).copy()   # legacy, unverifiable
    payload = data[8:]
    if (zlib.crc32(payload) & 0xFFFFFFFF) != expected:
        raise CorruptIndexError(
            f"segment file [{seg_id}.liv] checksum mismatch")
    try:
        return np.load(io.BytesIO(payload)).copy()
    except ValueError as e:
        raise CorruptIndexError(
            f"segment file [{seg_id}.liv] is undecodable: {e}") from e


def quant_sidecar_name(seg_id: str, field: str) -> str:
    return f"{seg_id}.{field}.quant"


def _encode_quant(qt) -> bytes:
    buf = io.BytesIO()
    meta = json.dumps({"width": int(qt.width), "dtype": qt.dtype,
                       "avgdl": float(qt.avgdl), "stats": qt.stats},
                      sort_keys=True).encode()
    np.savez(buf, qvals=qt.qvals, scales=qt.scales,
             exact_vals=qt.exact_vals, exact_offsets=qt.exact_offsets,
             packed=qt.packed, base=qt.base,
             meta=np.frombuffer(meta, dtype=np.uint8))
    payload = buf.getvalue()
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    return f"{crc:08x}".encode() + payload


def save_quantized_tables(dirpath: str, seg_id: str, field: str, qt):
    """Persist one field's quantized tables (index/codec.py) as a
    ``<seg_id>.<field>.quant`` sidecar.  Like ``.liv`` it lives OUTSIDE
    the immutable commit manifest — it is an avgdl-dependent cache a
    refresh/merge can obsolete — so it carries its own CRC32 header and
    the reader treats any mismatch as 'absent', never as a failure."""
    os.makedirs(dirpath, exist_ok=True)
    write_durable(
        os.path.join(dirpath, quant_sidecar_name(seg_id, field)),
        _encode_quant(qt))


def load_quantized_tables(dirpath: str, seg_id: str, field: str,
                          avgdl: float | None = None):
    """Load a ``.quant`` sidecar.  Returns None when the file is absent
    or was built for a different avgdl (stale — the caller rebuilds);
    raises ``CorruptIndexError`` naming the file on checksum/decode
    failure (the caller degrades to recompute-and-rewrite)."""
    from opensearch_tpu.index.codec import QuantizedPostings
    name = quant_sidecar_name(seg_id, field)
    path = os.path.join(dirpath, name)
    if not os.path.exists(path):
        return None
    with open(path, "rb") as f:
        data = f.read()
    try:
        expected = int(data[:8], 16)
    except ValueError as e:
        raise CorruptIndexError(
            f"segment file [{name}] has no checksum header") from e
    payload = data[8:]
    if (zlib.crc32(payload) & 0xFFFFFFFF) != expected:
        raise CorruptIndexError(
            f"segment file [{name}] checksum mismatch")
    try:
        z = np.load(io.BytesIO(payload))
        meta = json.loads(z["meta"].tobytes().decode())
        qt = QuantizedPostings(
            qvals=z["qvals"], scales=z["scales"],
            exact_vals=z["exact_vals"], exact_offsets=z["exact_offsets"],
            packed=z["packed"], base=z["base"],
            width=int(meta["width"]), dtype=meta["dtype"],
            avgdl=float(meta["avgdl"]),
            stats=dict(meta.get("stats") or {}))
    except (ValueError, KeyError) as e:
        raise CorruptIndexError(
            f"segment file [{name}] is undecodable: {e}") from e
    if avgdl is not None and float(np.float32(avgdl)) != qt.avgdl:
        return None        # stale (avgdl moved under a refresh/merge)
    return qt


def save_segment(seg: Segment, dirpath: str, codec: str = "default"):
    """``codec`` mirrors the reference's two stored-field codecs (ref
    index/codec/CodecService.java:46 — LZ4 "default" vs zstd/DEFLATE
    "best_compression", the index.codec setting): best_compression
    deflates the arrays (compressed npz) and the _source blob, trading
    write CPU for disk; the read path is self-describing via meta.

    Commit discipline: data files land tmp+fsync+rename (invisible to
    readers — nothing references them yet), then the manifest rename is
    the single atomic commit point.  A crash between any two steps
    leaves the previous committed state fully intact."""
    if codec not in CODECS:
        raise OpenSearchTpuError(f"unknown codec [{codec}]")
    os.makedirs(dirpath, exist_ok=True)
    arrays, meta, src_bytes = _segment_encode(seg)
    compress = codec == "best_compression"
    if compress:
        meta["src_codec"] = "zlib"
        src_bytes = zlib.compress(src_bytes, 6)
    buf = io.BytesIO()
    (np.savez_compressed if compress else np.savez)(buf, **arrays)
    entries = {}
    for suffix, data in ((".src", src_bytes), (".npz", buf.getvalue()),
                         (".json", json.dumps(meta).encode())):
        name = seg.seg_id + suffix
        write_durable(os.path.join(dirpath, name), data)
        entries[name] = file_checksum(data)
    write_segment_manifest(dirpath, seg.seg_id, entries)
    # freshly-saved segments persist quantized sidecars here too, not
    # only after a load (mirrors load_segment)
    seg.quant_dir = dirpath


def save_live(seg: Segment, dirpath: str):
    """Rewrite only the live-docs bitmap (Lucene .liv analog); the CRC
    header makes the file self-verifying (see ``_decode_liv``)."""
    write_durable(os.path.join(dirpath, seg.seg_id + ".liv"),
                  _encode_liv(seg.live))


def load_segment(dirpath: str, seg_id: str) -> Segment:
    """Read, VERIFY (against the commit manifest), then decode — a
    checksum mismatch raises ``CorruptIndexError`` naming the file
    before any bytes are interpreted (Store.verify-on-open)."""
    manifest = read_segment_manifest(dirpath, seg_id)
    try:
        json_b = _read_verified(dirpath, seg_id + ".json", manifest)
        npz_b = _read_verified(dirpath, seg_id + ".npz", manifest)
        src_blob = _read_verified(dirpath, seg_id + ".src", manifest)
        meta = json.loads(json_b.decode())
        z = np.load(io.BytesIO(npz_b))
        if meta.get("src_codec") == "zlib":
            src_blob = zlib.decompress(src_blob)
    except CorruptIndexError:
        raise
    except (OSError, ValueError, zlib.error) as e:
        raise CorruptIndexError(f"cannot read segment [{seg_id}]: {e}") from e
    seg = _segment_decode(seg_id, meta, z, src_blob)
    liv_path = os.path.join(dirpath, seg_id + ".liv")
    if os.path.exists(liv_path):
        with open(liv_path, "rb") as f:
            seg.live = _decode_liv(seg_id, f.read())
    # quantized-table sidecars load lazily from here (and fresh builds
    # write back) — see Segment.quantized_table
    seg.quant_dir = dirpath
    return seg


def verify_segment(dirpath: str, seg_id: str) -> bool:
    """Checksum-only pass over a committed segment's on-disk files —
    the ``Store.verify`` analog (no decoding, no allocation of decoded
    structures).  Returns False when the segment predates manifests
    (nothing to verify against); raises ``CorruptIndexError`` naming
    the first bad file."""
    manifest = read_segment_manifest(dirpath, seg_id)
    liv_path = os.path.join(dirpath, seg_id + ".liv")
    if os.path.exists(liv_path):
        with open(liv_path, "rb") as f:
            _decode_liv(seg_id, f.read())
    if os.path.isdir(dirpath):
        # self-verified sidecars (CRC header, outside the manifest)
        for fname in sorted(os.listdir(dirpath)):
            if fname.startswith(seg_id + ".") and fname.endswith(".quant"):
                field = fname[len(seg_id) + 1: -len(".quant")]
                load_quantized_tables(dirpath, seg_id, field)
    if manifest is None:
        return False
    for name in sorted(manifest["files"]):
        _read_verified(dirpath, name, manifest)
    return True


# -- corruption markers (Store.markStoreCorrupted analog) -------------------

_MARKER_PREFIX = "corrupted_"


def write_corruption_marker(dirpath: str, seg_id: str, reason: str):
    """Persist the verdict so the store refuses to reopen until the copy
    is dropped and re-recovered (Store.failIfCorrupted)."""
    os.makedirs(dirpath, exist_ok=True)
    write_durable(
        os.path.join(dirpath, f"{_MARKER_PREFIX}{seg_id}.json"),
        json.dumps({"segment": seg_id, "reason": reason},
                   sort_keys=True).encode())


def find_corruption_markers(dirpath: str) -> list[dict]:
    out = []
    if not os.path.isdir(dirpath):
        return out
    for fname in sorted(os.listdir(dirpath)):
        if not fname.startswith(_MARKER_PREFIX) \
                or not fname.endswith(".json") or fname.endswith(".tmp"):
            continue
        try:
            with open(os.path.join(dirpath, fname), "rb") as f:
                out.append(json.loads(f.read().decode()))
        except (OSError, ValueError):
            out.append({"segment": fname[len(_MARKER_PREFIX):-len(".json")],
                        "reason": "unreadable corruption marker"})
    return out


def clear_corruption_markers(dirpath: str):
    if not os.path.isdir(dirpath):
        return
    for fname in list(os.listdir(dirpath)):
        if fname.startswith(_MARKER_PREFIX) and fname.endswith(".json"):
            os.remove(os.path.join(dirpath, fname))


# -- wire serialization (recovery / segment replication file copy) ----------


def segment_to_blobs(seg: Segment) -> dict:
    """Serialize a segment to wire-shippable blobs {json, npz, src} — the
    'file copy' unit of segment replication and peer recovery phase 1
    (ref indices/recovery/RecoverySourceHandler.java:105).  Each blob's
    length + CRC32 travels alongside, so the receiving replica verifies
    the copy before installing it (RecoveryTarget's per-chunk checksum)."""
    arrays, meta, src_bytes = _segment_encode(seg)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    blobs = {"json": json.dumps(meta).encode(), "npz": buf.getvalue(),
             "src": src_bytes}
    blobs["checksums"] = {k: file_checksum(v) for k, v in blobs.items()}
    return blobs


def segment_from_blobs(blobs: dict) -> Segment:
    checksums = blobs.get("checksums")
    try:
        if checksums is not None:
            for part in ("json", "npz", "src"):
                want = checksums.get(part)
                if want is not None:
                    _verify_bytes(f"<wire>.{part}", blobs[part], want)
        meta = json.loads(blobs["json"].decode())
        z = np.load(io.BytesIO(blobs["npz"]))
    except CorruptIndexError:
        raise
    except (KeyError, ValueError) as e:
        raise CorruptIndexError(f"cannot decode segment blobs: {e}") from e
    return _segment_decode(meta["seg_id"], meta, z, blobs["src"])


def _segment_decode(seg_id: str, meta: dict, z, src_blob: bytes) -> Segment:
    seg = Segment(seg_id, meta["n_docs"])
    seg.doc_ids = list(meta["doc_ids"])
    seg.id_to_local = {d: i for i, d in enumerate(seg.doc_ids)}
    seg.routings = {int(k): v
                    for k, v in (meta.get("routings") or {}).items()}
    for f, wmap in (meta.get("completion_weights") or {}).items():
        out = {}
        for key, w in wmap.items():
            local, _, text = key.partition("\x00")
            out[(int(local), text)] = w
        seg.completion_weights[f] = out
    seg.seq_nos = z["seq_nos"]
    seg.versions = z["versions"]
    seg.live = z["live"].copy()
    src_offsets = z["src_offsets"]
    seg.sources = [src_blob[src_offsets[i]: src_offsets[i + 1]]
                   for i in range(meta["n_docs"])]
    for f, m in meta["postings"].items():
        seg.postings[f] = PostingsField(
            terms={t: i for i, t in enumerate(m["terms"])},
            df=z[f"p|{f}|df"], offsets=z[f"p|{f}|offsets"],
            doc_ids=z[f"p|{f}|doc_ids"], tfs=z[f"p|{f}|tfs"],
            pos_offsets=z[f"p|{f}|pos_offsets"],
            positions=z[f"p|{f}|positions"], doc_lens=z[f"p|{f}|doc_lens"],
            total_len=m["total_len"], docs_with_field=m["docs_with_field"],
            has_norms=m["has_norms"], present=z[f"p|{f}|present"])
    for f, m in meta["numeric"].items():
        seg.numeric_dv[f] = NumericDV(
            kind=m["kind"], offsets=z[f"n|{f}|offsets"],
            values=z[f"n|{f}|values"], value_docs=z[f"n|{f}|value_docs"],
            minv=z[f"n|{f}|minv"], maxv=z[f"n|{f}|maxv"],
            exists=z[f"n|{f}|exists"])
    for f, m in meta["ordinal"].items():
        seg.ordinal_dv[f] = OrdinalDV(
            ord_terms=list(m["ord_terms"]),
            term_to_ord={t: i for i, t in enumerate(m["ord_terms"])},
            offsets=z[f"o|{f}|offsets"], ords=z[f"o|{f}|ords"],
            value_docs=z[f"o|{f}|value_docs"], min_ord=z[f"o|{f}|min_ord"],
            max_ord=z[f"o|{f}|max_ord"], exists=z[f"o|{f}|exists"])
    for f, m in meta["vector"].items():
        seg.vector_dv[f] = VectorDV(
            values=z[f"v|{f}|values"], exists=z[f"v|{f}|exists"],
            dim=m["dim"], similarity=m["similarity"])
    for path, m in meta.get("nested", {}).items():
        from opensearch_tpu.index.segment import NestedBlock
        block = NestedBlock(obj_to_doc=z[f"x|{path}|obj_to_doc"])
        for f in m["numeric_fields"]:
            block.numeric[f] = (z[f"x|{path}|n|{f}|values"],
                                z[f"x|{path}|n|{f}|objs"])
        for f in m["ordinal_fields"]:
            block.ordinal[f] = (list(m["ord_terms"][f]),
                                z[f"x|{path}|o|{f}|ords"],
                                z[f"x|{path}|o|{f}|objs"])
        seg.nested[path] = block
    for f, m in meta["geo"].items():
        seg.geo_dv[f] = GeoDV(
            offsets=z[f"g|{f}|offsets"], lats=z[f"g|{f}|lats"],
            lons=z[f"g|{f}|lons"], value_docs=z[f"g|{f}|value_docs"],
            exists=z[f"g|{f}|exists"])
    return seg


def delete_segment_files(dirpath: str, seg_id: str):
    # manifest first: once it's gone the segment is uncommitted, so a
    # crash mid-deletion can't leave a manifest naming missing files
    for ext in (MANIFEST_SUFFIX, ".npz", ".json", ".src", ".liv"):
        p = os.path.join(dirpath, seg_id + ext)
        if os.path.exists(p):
            os.remove(p)
    if os.path.isdir(dirpath):
        for fname in list(os.listdir(dirpath)):
            if fname.startswith(seg_id + ".") and fname.endswith(".quant"):
                os.remove(os.path.join(dirpath, fname))
