"""On-disk segment format.

Analog of the Lucene codec + ``index/store/Store.java``: one ``.npz`` of
flat arrays + one ``.json`` of dictionaries/metadata + one ``.src`` blob of
concatenated _source bytes per segment.  Arrays are written exactly as the
in-memory Segment holds them (the device staging re-pads on load), and the
live-docs bitmap is rewritten in place on delete-commit like Lucene's
``.liv`` files.
"""

from __future__ import annotations

import json
import os
import zlib

import numpy as np

from opensearch_tpu.common.errors import OpenSearchTpuError
from opensearch_tpu.index.segment import (
    GeoDV,
    NumericDV,
    OrdinalDV,
    PostingsField,
    Segment,
    VectorDV,
)


class CorruptIndexError(OpenSearchTpuError):
    status = 500


def _segment_encode(seg: Segment):
    """Split a Segment into (arrays, meta, src_bytes) — shared by the
    on-disk writer and the wire serializer (segment replication file copy,
    ref indices/replication/SegmentReplicationTargetService.java:208)."""
    arrays: dict[str, np.ndarray] = {
        "seq_nos": seg.seq_nos, "versions": seg.versions, "live": seg.live,
    }
    meta = {"seg_id": seg.seg_id, "n_docs": seg.n_docs,
            "doc_ids": seg.doc_ids,
            "routings": {str(k): v for k, v in seg.routings.items()},
            "completion_weights": {
                f: {f"{local}\x00{text}": w
                    for (local, text), w in wmap.items()}
                for f, wmap in seg.completion_weights.items()},
            "postings": {}, "numeric": {}, "ordinal": {}, "vector": {},
            "geo": {}, "nested": {}}

    src_offsets = np.zeros(len(seg.sources) + 1, dtype=np.int64)
    for i, b in enumerate(seg.sources):
        src_offsets[i + 1] = src_offsets[i] + len(b)
    arrays["src_offsets"] = src_offsets

    for f, pf in seg.postings.items():
        meta["postings"][f] = {
            "terms": list(pf.terms), "total_len": pf.total_len,
            "docs_with_field": pf.docs_with_field, "has_norms": pf.has_norms,
        }
        for k in ("df", "offsets", "doc_ids", "tfs", "pos_offsets",
                  "positions", "doc_lens", "present"):
            arrays[f"p|{f}|{k}"] = getattr(pf, k)
    for f, dv in seg.numeric_dv.items():
        meta["numeric"][f] = {"kind": dv.kind}
        for k in ("offsets", "values", "value_docs", "minv", "maxv", "exists"):
            arrays[f"n|{f}|{k}"] = getattr(dv, k)
    for f, dv in seg.ordinal_dv.items():
        meta["ordinal"][f] = {"ord_terms": dv.ord_terms}
        for k in ("offsets", "ords", "value_docs", "min_ord", "max_ord",
                  "exists"):
            arrays[f"o|{f}|{k}"] = getattr(dv, k)
    for f, dv in seg.vector_dv.items():
        meta["vector"][f] = {"dim": dv.dim, "similarity": dv.similarity}
        arrays[f"v|{f}|values"] = dv.values
        arrays[f"v|{f}|exists"] = dv.exists
    for f, dv in seg.geo_dv.items():
        meta["geo"][f] = {}
        for k in ("offsets", "lats", "lons", "value_docs", "exists"):
            arrays[f"g|{f}|{k}"] = getattr(dv, k)
    for path, block in seg.nested.items():
        meta["nested"][path] = {
            "numeric_fields": sorted(block.numeric),
            "ordinal_fields": sorted(block.ordinal),
            "ord_terms": {f: block.ordinal[f][0] for f in block.ordinal},
        }
        arrays[f"x|{path}|obj_to_doc"] = block.obj_to_doc
        for f, (values, value_objs) in block.numeric.items():
            arrays[f"x|{path}|n|{f}|values"] = values
            arrays[f"x|{path}|n|{f}|objs"] = value_objs
        for f, (_terms, ords, value_objs) in block.ordinal.items():
            arrays[f"x|{path}|o|{f}|ords"] = ords
            arrays[f"x|{path}|o|{f}|objs"] = value_objs
    return arrays, meta, b"".join(seg.sources)


CODECS = ("default", "best_compression")


def save_segment(seg: Segment, dirpath: str, codec: str = "default"):
    """``codec`` mirrors the reference's two stored-field codecs (ref
    index/codec/CodecService.java:46 — LZ4 "default" vs zstd/DEFLATE
    "best_compression", the index.codec setting): best_compression
    deflates the arrays (compressed npz) and the _source blob, trading
    write CPU for disk; the read path is self-describing via meta."""
    if codec not in CODECS:
        raise OpenSearchTpuError(f"unknown codec [{codec}]")
    os.makedirs(dirpath, exist_ok=True)
    arrays, meta, src_bytes = _segment_encode(seg)
    compress = codec == "best_compression"
    if compress:
        meta["src_codec"] = "zlib"
        src_bytes = zlib.compress(src_bytes, 6)
    base = os.path.join(dirpath, seg.seg_id)
    with open(base + ".src.tmp", "wb") as f:
        f.write(src_bytes)
        f.flush()
        os.fsync(f.fileno())
    os.replace(base + ".src.tmp", base + ".src")
    with open(base + ".npz.tmp", "wb") as f:
        (np.savez_compressed if compress else np.savez)(f, **arrays)
        f.flush()
        os.fsync(f.fileno())
    os.replace(base + ".npz.tmp", base + ".npz")
    with open(base + ".json.tmp", "w") as f:
        json.dump(meta, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(base + ".json.tmp", base + ".json")


def save_live(seg: Segment, dirpath: str):
    """Rewrite only the live-docs bitmap (Lucene .liv analog)."""
    base = os.path.join(dirpath, seg.seg_id)
    with open(base + ".liv.tmp", "wb") as f:
        np.save(f, seg.live)
        f.flush()
        os.fsync(f.fileno())
    os.replace(base + ".liv.tmp", base + ".liv")


def load_segment(dirpath: str, seg_id: str) -> Segment:
    base = os.path.join(dirpath, seg_id)
    try:
        with open(base + ".json") as f:
            meta = json.load(f)
        z = np.load(base + ".npz")
        with open(base + ".src", "rb") as f:
            src_blob = f.read()
        if meta.get("src_codec") == "zlib":
            src_blob = zlib.decompress(src_blob)
    except (OSError, ValueError, zlib.error) as e:
        raise CorruptIndexError(f"cannot read segment [{seg_id}]: {e}") from e
    seg = _segment_decode(seg_id, meta, z, src_blob)
    if os.path.exists(base + ".liv"):
        seg.live = np.load(base + ".liv").copy()
    return seg


def segment_to_blobs(seg: Segment) -> dict:
    """Serialize a segment to wire-shippable blobs {json, npz, src} — the
    'file copy' unit of segment replication and peer recovery phase 1
    (ref indices/recovery/RecoverySourceHandler.java:105)."""
    import io

    arrays, meta, src_bytes = _segment_encode(seg)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return {"json": json.dumps(meta).encode(), "npz": buf.getvalue(),
            "src": src_bytes}


def segment_from_blobs(blobs: dict) -> Segment:
    import io

    try:
        meta = json.loads(blobs["json"].decode())
        z = np.load(io.BytesIO(blobs["npz"]))
    except (KeyError, ValueError) as e:
        raise CorruptIndexError(f"cannot decode segment blobs: {e}") from e
    return _segment_decode(meta["seg_id"], meta, z, blobs["src"])


def _segment_decode(seg_id: str, meta: dict, z, src_blob: bytes) -> Segment:
    seg = Segment(seg_id, meta["n_docs"])
    seg.doc_ids = list(meta["doc_ids"])
    seg.id_to_local = {d: i for i, d in enumerate(seg.doc_ids)}
    seg.routings = {int(k): v
                    for k, v in (meta.get("routings") or {}).items()}
    for f, wmap in (meta.get("completion_weights") or {}).items():
        out = {}
        for key, w in wmap.items():
            local, _, text = key.partition("\x00")
            out[(int(local), text)] = w
        seg.completion_weights[f] = out
    seg.seq_nos = z["seq_nos"]
    seg.versions = z["versions"]
    seg.live = z["live"].copy()
    src_offsets = z["src_offsets"]
    seg.sources = [src_blob[src_offsets[i]: src_offsets[i + 1]]
                   for i in range(meta["n_docs"])]
    for f, m in meta["postings"].items():
        seg.postings[f] = PostingsField(
            terms={t: i for i, t in enumerate(m["terms"])},
            df=z[f"p|{f}|df"], offsets=z[f"p|{f}|offsets"],
            doc_ids=z[f"p|{f}|doc_ids"], tfs=z[f"p|{f}|tfs"],
            pos_offsets=z[f"p|{f}|pos_offsets"],
            positions=z[f"p|{f}|positions"], doc_lens=z[f"p|{f}|doc_lens"],
            total_len=m["total_len"], docs_with_field=m["docs_with_field"],
            has_norms=m["has_norms"], present=z[f"p|{f}|present"])
    for f, m in meta["numeric"].items():
        seg.numeric_dv[f] = NumericDV(
            kind=m["kind"], offsets=z[f"n|{f}|offsets"],
            values=z[f"n|{f}|values"], value_docs=z[f"n|{f}|value_docs"],
            minv=z[f"n|{f}|minv"], maxv=z[f"n|{f}|maxv"],
            exists=z[f"n|{f}|exists"])
    for f, m in meta["ordinal"].items():
        seg.ordinal_dv[f] = OrdinalDV(
            ord_terms=list(m["ord_terms"]),
            term_to_ord={t: i for i, t in enumerate(m["ord_terms"])},
            offsets=z[f"o|{f}|offsets"], ords=z[f"o|{f}|ords"],
            value_docs=z[f"o|{f}|value_docs"], min_ord=z[f"o|{f}|min_ord"],
            max_ord=z[f"o|{f}|max_ord"], exists=z[f"o|{f}|exists"])
    for f, m in meta["vector"].items():
        seg.vector_dv[f] = VectorDV(
            values=z[f"v|{f}|values"], exists=z[f"v|{f}|exists"],
            dim=m["dim"], similarity=m["similarity"])
    for path, m in meta.get("nested", {}).items():
        from opensearch_tpu.index.segment import NestedBlock
        block = NestedBlock(obj_to_doc=z[f"x|{path}|obj_to_doc"])
        for f in m["numeric_fields"]:
            block.numeric[f] = (z[f"x|{path}|n|{f}|values"],
                                z[f"x|{path}|n|{f}|objs"])
        for f in m["ordinal_fields"]:
            block.ordinal[f] = (list(m["ord_terms"][f]),
                                z[f"x|{path}|o|{f}|ords"],
                                z[f"x|{path}|o|{f}|objs"])
        seg.nested[path] = block
    for f, m in meta["geo"].items():
        seg.geo_dv[f] = GeoDV(
            offsets=z[f"g|{f}|offsets"], lats=z[f"g|{f}|lats"],
            lons=z[f"g|{f}|lons"], value_docs=z[f"g|{f}|value_docs"],
            exists=z[f"g|{f}|exists"])
    return seg


def delete_segment_files(dirpath: str, seg_id: str):
    for ext in (".npz", ".json", ".src", ".liv"):
        p = os.path.join(dirpath, seg_id + ext)
        if os.path.exists(p):
            os.remove(p)
