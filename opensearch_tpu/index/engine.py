"""Per-shard engine: versioned CRUD with seq-nos, translog durability,
NRT refresh, commits, realtime GET.

Analog of ``index/engine/InternalEngine.java`` (index :845, plan branches
:909-920, indexIntoLucene :1107) + ``LiveVersionMap``: documents buffer in
a host-side "hot" list and become an immutable array segment on refresh
(the incremental-NRT-vs-immutable-device-arrays design from SURVEY §7.3);
deletes tombstone the owning segment's live bitmap at refresh; the version
map serves realtime GET and optimistic concurrency between refreshes.
"""

from __future__ import annotations

import json
import os
import threading
import uuid
from dataclasses import dataclass
from typing import Optional

import numpy as np

from opensearch_tpu.common.errors import (
    EngineClosedError,
    IllegalArgumentError,
    MapperParsingError,
    VersionConflictError,
)
from opensearch_tpu.index.segment import Segment, SegmentWriter
from opensearch_tpu.index.store import (
    CorruptIndexError,
    delete_segment_files,
    find_corruption_markers,
    load_segment,
    save_live,
    save_segment,
    segment_from_blobs,
    segment_to_blobs,
    verify_segment,
    write_corruption_marker,
)
from opensearch_tpu.index.translog import Translog
from opensearch_tpu.mapping.mapper import DocumentMapper, ParsedDocument
from opensearch_tpu.search.executor import ShardSearcher


@dataclass
class VersionEntry:
    seq_no: int
    version: int
    deleted: bool
    hot_idx: int = -1                # >=0 while the doc lives in the hot buffer


@dataclass
class OpResult:
    doc_id: str
    seq_no: int
    version: int
    result: str                      # created | updated | deleted | not_found
    primary_term: int = 1            # the term the op executed under


class InternalEngine:
    """Single-writer-per-shard engine (writes serialized by a lock, like
    the reference's per-shard indexing semantics under operation permits)."""

    COMMIT_FILE = "commit.json"

    def __init__(self, data_path: str, mapper: DocumentMapper,
                 index_name: str = "index", shard_id: int = 0,
                 durability: str = "request", codec: str = "default"):
        self.data_path = data_path
        self.mapper = mapper
        self.codec = codec
        self.index_name = index_name
        self.shard_id = shard_id
        self.primary_term = 1
        self._lock = threading.RLock()
        self._closed = False
        # search-only replica engine (the ingest/search tier split):
        # segments arrive exclusively via remote-store checkpoint
        # installs — every write entry point refuses, keeping searchers
        # stateless and out of the replication stream entirely
        self.search_only = False
        # set when the on-disk store failed verification (marker found or
        # checksum mismatch): the engine refuses reads/writes so a corrupt
        # copy can never serve wrong data (Store.failIfCorrupted)
        self.corruption: Optional[CorruptIndexError] = None
        self.segments: list[Segment] = []
        self._hot: list[Optional[ParsedDocument]] = []
        self._version_map: dict[str, VersionEntry] = {}
        self._pending_deletes: list[tuple[Segment, int]] = []
        self._seq_no = -1
        # local checkpoint: highest seq_no below which EVERY op has been
        # processed on this copy (LocalCheckpointTracker analog) — the
        # value replicas report back so the primary can compute the
        # global checkpoint.  Non-contiguous arrivals park in
        # _pending_seqs until the gap fills.
        self._local_ckpt = -1
        self._pending_seqs: set[int] = set()
        # global checkpoint: highest seq_no known durable on EVERY
        # in-sync copy (GlobalCheckpointTracker analog).  Computed by the
        # primary, piggybacked to replicas on replication ops; ops above
        # it are the rollback set on demotion.
        self.global_checkpoint = -1
        # doc id -> primary term of the op that last touched it; the
        # (primary_term, seq_no) half of the durability audit's per-copy
        # digest.  Terms == 1 are implicit (kept out of commits).
        self._doc_terms: dict[str, int] = {}
        # replica mode: primary-replicated ops not yet covered by an
        # installed segment checkpoint, keyed by seq_no
        self._replica_ops: dict[int, dict] = {}
        self._persisted_segments: set[str] = set()
        self._live_dirty: set[str] = set()
        # files superseded by a merge: deleted only AFTER the next commit
        # point lands (Lucene keeps old files until commit)
        self._obsolete_files: set[str] = set()
        self._seg_counter = 0
        # lease id (replica node) -> lowest retained seq_no; leases pin
        # translog generations past flush (RetentionLease analog)
        self.retention_leases: dict[str, int] = {}
        # generation -> max seq_no it contains (recorded at roll time) so
        # lease-aware trimming deletes exactly the generations every
        # lease has moved past
        self._gen_max_seq: dict[int, int] = {}
        # engine-unique segment-id prefix: segments INSTALLED from another
        # engine (segment replication / recovery) keep their foreign ids,
        # so locally-built ids must never collide with them — a promoted
        # replica builds segments alongside ids minted by the old primary
        self._engine_uid = uuid.uuid4().hex[:6]
        self._searcher: Optional[ShardSearcher] = None
        self._writer = SegmentWriter()

        os.makedirs(data_path, exist_ok=True)
        self.translog = Translog(os.path.join(data_path, "translog"),
                                 durability=durability)
        self._recover()

    # -- lifecycle --------------------------------------------------------

    def _recover(self):
        """Load the last commit point, then replay translog ops newer than
        it (RecoverySourceHandler phase-2 analog for the local shard).

        A store with a corruption marker, or one whose checksums fail on
        load, does NOT open: ``self.corruption`` carries the verdict and
        every read/write raises it until the copy is dropped and
        re-recovered (Store.failIfCorrupted / CorruptedFileException)."""
        commit_path = os.path.join(self.data_path, self.COMMIT_FILE)
        seg_dir = os.path.join(self.data_path, "segments")
        markers = find_corruption_markers(seg_dir)
        if markers:
            self.corruption = CorruptIndexError(
                f"[{self.index_name}][{self.shard_id}] store is marked "
                f"corrupted: {markers[0].get('reason', 'unknown')}")
            return
        committed_seq = -1
        if os.path.exists(commit_path):
            with open(commit_path) as f:
                commit = json.load(f)
            committed_seq = commit["max_seq_no"]
            self._seg_counter = commit.get("seg_counter", 0)
            self.primary_term = max(self.primary_term,
                                    int(commit.get("primary_term", 1)))
            self._doc_terms = {str(k): int(v) for k, v in
                               (commit.get("doc_terms") or {}).items()}
            for seg_id in commit["segments"]:
                try:
                    seg = load_segment(seg_dir, seg_id)
                except CorruptIndexError as e:
                    write_corruption_marker(seg_dir, seg_id, str(e))
                    self.corruption = e
                    self.segments = []
                    self._persisted_segments.clear()
                    return
                self.segments.append(seg)
                self._persisted_segments.add(seg_id)
            self._seq_no = committed_seq
            self._advance_local_ckpt_to(committed_seq)
            # GC segment files the commit doesn't reference (a crash
            # between commit write and obsolete-file deletion leaks them)
            if os.path.isdir(seg_dir):
                referenced = set(commit["segments"])
                for fname in os.listdir(seg_dir):
                    seg_id = fname.rsplit(".", 1)[0]
                    if seg_id.endswith(".src"):
                        seg_id = seg_id[:-4]
                    if seg_id not in referenced:
                        os.remove(os.path.join(seg_dir, fname))
        for op in self.translog.read_ops(committed_seq):
            self._replay(op)

    def _replay(self, op: dict):
        if op["op"] == "index":
            self._do_index(op["id"], op["source"], routing=op.get("routing"),
                           seq_no=op["seq_no"], version=op["version"],
                           record=False)
        elif op["op"] == "delete":
            self._do_delete(op["id"], seq_no=op["seq_no"],
                            version=op["version"], record=False)
        # an op recorded under an older primary keeps that term across
        # replay — replayed history must digest identically on every copy
        if op.get("primary_term") is not None:
            self._doc_terms[str(op["id"])] = int(op["primary_term"])
        self._seq_no = max(self._seq_no, op["seq_no"])
        self._mark_seq_processed(int(op["seq_no"]))

    # -- checkpoint trackers ----------------------------------------------

    def _mark_seq_processed(self, seq: int):
        """Advance the local checkpoint past ``seq`` once contiguous
        (LocalCheckpointTracker.markSeqNoAsProcessed analog)."""
        if seq == self._local_ckpt + 1:
            self._local_ckpt = seq
            while self._local_ckpt + 1 in self._pending_seqs:
                self._local_ckpt += 1
                self._pending_seqs.discard(self._local_ckpt)
        elif seq > self._local_ckpt:
            self._pending_seqs.add(seq)

    def _advance_local_ckpt_to(self, seq: int):
        """A checkpoint install covers EVERY op <= seq: jump the tracker
        forward even over gaps this copy never saw individually."""
        if seq > self._local_ckpt:
            self._local_ckpt = int(seq)
        self._pending_seqs = {s for s in self._pending_seqs
                              if s > self._local_ckpt}
        while self._local_ckpt + 1 in self._pending_seqs:
            self._local_ckpt += 1
            self._pending_seqs.discard(self._local_ckpt)

    @property
    def local_checkpoint(self) -> int:
        with self._lock:
            return self._local_ckpt

    def update_global_checkpoint(self, gckpt: int):
        """Monotonic: the global checkpoint only advances (the primary
        recomputes it as min over in-sync local checkpoints; replicas
        learn it piggybacked on replication ops)."""
        with self._lock:
            self.global_checkpoint = max(self.global_checkpoint, int(gckpt))

    def close(self):
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self.translog.close()

    def _ensure_open(self):
        if self._closed:
            raise EngineClosedError(f"engine for [{self.index_name}] is closed")
        if self.corruption is not None:
            raise self.corruption

    def _ensure_writeable(self):
        self._ensure_open()
        if self.search_only:
            from opensearch_tpu.common.errors import IllegalArgumentError
            raise IllegalArgumentError(
                f"[{self.index_name}][{self.shard_id}] is a search-only "
                "replica: writes are rejected on the search tier")

    def verify_store(self):
        """Full checksum pass over every persisted segment's on-disk
        files (Store.verify analog).  Detected corruption writes a
        ``corrupted_<seg>`` marker, poisons the engine, and raises —
        the caller (ClusterNode) runs the copy-failover protocol."""
        with self._lock:
            if self._closed:
                raise EngineClosedError(
                    f"engine for [{self.index_name}] is closed")
            if self.corruption is not None:
                raise self.corruption
            seg_dir = os.path.join(self.data_path, "segments")
            markers = find_corruption_markers(seg_dir)
            if markers:
                self.corruption = CorruptIndexError(
                    f"[{self.index_name}][{self.shard_id}] store is marked "
                    f"corrupted: {markers[0].get('reason', 'unknown')}")
                raise self.corruption
            for seg_id in sorted(self._persisted_segments):
                try:
                    verify_segment(seg_dir, seg_id)
                except CorruptIndexError as e:
                    write_corruption_marker(seg_dir, seg_id, str(e))
                    self.corruption = e
                    raise

    # -- version plumbing -------------------------------------------------

    def _current_entry(self, doc_id: str) -> Optional[VersionEntry]:
        e = self._version_map.get(doc_id)
        if e is not None:
            return e
        for seg in reversed(self.segments):
            local = seg.id_to_local.get(doc_id)
            if local is not None and seg.live[local]:
                return VersionEntry(seq_no=int(seg.seq_nos[local]),
                                    version=int(seg.versions[local]),
                                    deleted=False)
        return None

    def _check_conflicts(self, doc_id, entry, if_seq_no, if_primary_term,
                         version, version_type):
        if if_seq_no is not None or if_primary_term is not None:
            cur_seq = entry.seq_no if entry is not None and not entry.deleted else -1
            if if_seq_no is not None and cur_seq != if_seq_no:
                raise VersionConflictError(doc_id, f"seq_no [{if_seq_no}]",
                                           f"seq_no [{cur_seq}]")
            if if_primary_term is not None and if_primary_term != self.primary_term:
                raise VersionConflictError(
                    doc_id, f"primary_term [{if_primary_term}]",
                    f"primary_term [{self.primary_term}]")
        if version is not None:
            cur = entry.version if entry is not None and not entry.deleted else 0
            if version_type == "external":
                if version <= cur:
                    raise VersionConflictError(doc_id, f"> [{cur}]", version)
            elif version_type == "external_gte":
                if version < cur:
                    raise VersionConflictError(doc_id, f">= [{cur}]",
                                               version)
            else:
                if cur != version:
                    raise VersionConflictError(doc_id, version, cur)

    # -- write path -------------------------------------------------------

    def index(self, doc_id: str, source: dict, routing: Optional[str] = None,
              if_seq_no: Optional[int] = None,
              if_primary_term: Optional[int] = None,
              version: Optional[int] = None,
              version_type: str = "internal") -> OpResult:
        import time as _time

        from opensearch_tpu.common.telemetry import metrics
        t0 = _time.monotonic()
        with self._lock:
            self._ensure_writeable()
            entry = self._current_entry(doc_id)
            self._check_conflicts(doc_id, entry, if_seq_no, if_primary_term,
                                  version, version_type)
            if version_type in ("external", "external_gte"):
                new_version = version
            else:
                new_version = (entry.version + 1
                               if entry is not None and not entry.deleted else 1)
            seq = self._seq_no + 1
            result = self._do_index(doc_id, source, routing=routing,
                                    seq_no=seq, version=new_version,
                                    record=True)
            self._seq_no = seq
            self._mark_seq_processed(seq)
        m = metrics()
        m.counter("indexing.ops").inc()
        m.histogram("indexing.index_ms").observe(
            (_time.monotonic() - t0) * 1000)
        return result

    def _do_index(self, doc_id, source, routing, seq_no, version,
                  record: bool) -> OpResult:
        doc = self.mapper.parse(str(doc_id), source, routing=routing)
        doc.seq_no = seq_no
        doc.version = version
        encoded = None
        if record:
            # serialize BEFORE mutating any state: a non-JSON source must
            # fail cleanly, not leave hot buffer and translog divergent
            try:
                encoded = self.translog.encode(
                    {"op": "index", "id": str(doc_id), "source": source,
                     "routing": routing, "seq_no": seq_no,
                     "version": version,
                     "primary_term": self.primary_term})
            except (TypeError, ValueError) as e:
                raise MapperParsingError(
                    f"source for [{doc_id}] is not JSON-serializable: {e}")
        prev = self._version_map.get(doc_id)
        cur = self._current_entry(doc_id)        # vm OR live segment doc
        existed = cur is not None and not cur.deleted
        if prev is not None and prev.hot_idx >= 0:
            self._hot[prev.hot_idx] = None       # replaced before refresh
        elif existed:
            self._tombstone_segments(doc_id)
        self._hot.append(doc)
        self._version_map[str(doc_id)] = VersionEntry(
            seq_no=seq_no, version=version, deleted=False,
            hot_idx=len(self._hot) - 1)
        if record:
            self.translog.add_encoded(encoded)
        self._doc_terms[str(doc_id)] = self.primary_term
        return OpResult(str(doc_id), seq_no, version,
                        "updated" if existed else "created",
                        primary_term=self.primary_term)

    def _tombstone_segments(self, doc_id: str):
        for seg in reversed(self.segments):
            local = seg.id_to_local.get(doc_id)
            if local is not None and seg.live[local]:
                self._pending_deletes.append((seg, local))
                return

    def delete(self, doc_id: str, if_seq_no: Optional[int] = None,
               if_primary_term: Optional[int] = None,
               version: Optional[int] = None,
               version_type: str = "internal") -> OpResult:
        with self._lock:
            self._ensure_writeable()
            entry = self._current_entry(doc_id)
            self._check_conflicts(doc_id, entry, if_seq_no, if_primary_term,
                                  version, version_type)
            if entry is None or entry.deleted:
                return OpResult(str(doc_id), self._seq_no, 1, "not_found",
                                primary_term=self.primary_term)
            new_version = (version
                           if version_type in ("external", "external_gte")
                           else entry.version + 1)
            seq = self._seq_no + 1
            result = self._do_delete(doc_id, seq_no=seq, version=new_version,
                                     record=True)
            self._seq_no = seq
            self._mark_seq_processed(seq)
            return result

    def _do_delete(self, doc_id, seq_no, version, record: bool) -> OpResult:
        prev = self._version_map.get(doc_id)
        if prev is not None and prev.hot_idx >= 0:
            self._hot[prev.hot_idx] = None
        else:
            self._tombstone_segments(doc_id)
        self._version_map[str(doc_id)] = VersionEntry(
            seq_no=seq_no, version=version, deleted=True)
        if record:
            self.translog.add({"op": "delete", "id": str(doc_id),
                               "seq_no": seq_no, "version": version,
                               "primary_term": self.primary_term})
        self._doc_terms[str(doc_id)] = self.primary_term
        return OpResult(str(doc_id), seq_no, version, "deleted",
                        primary_term=self.primary_term)

    def ensure_synced(self):
        """Durability barrier before acking (Translog.ensureSynced analog).
        Safe to call from concurrent write RPCs: the translog serializes
        its own sync/checkpoint internally."""
        self.translog.sync()

    # -- replica mode (segment replication, NRTReplicationEngine analog) --
    #
    # A replica does NOT index: replicated ops land in the translog (for
    # durability + realtime GET + promotion replay) and become searchable
    # only when the primary publishes a refresh checkpoint and the replica
    # installs the copied segments (ref index/engine/NRTReplicationEngine.java,
    # indices/replication/SegmentReplicationTargetService.java:208).

    def apply_replica_op(self, op: dict, fence: bool = True):
        """Apply one primary-replicated op: translog append + version-map
        entry + op buffer.  Fenced by primary term (a stale primary's ops
        are rejected, ref IndexShard.applyIndexOperationOnReplica:954).
        ``fence=False`` is for promotion-resync replay only: resync ops
        keep their ORIGINAL terms (which may be below this engine's,
        already bumped by the promotion) — the resync request itself was
        term-validated by the transport handler."""
        with self._lock:
            self._ensure_writeable()
            term = int(op.get("primary_term", 1))
            if fence and term < self.primary_term:
                raise VersionConflictError(
                    str(op.get("id")), f"primary term >= {self.primary_term}",
                    f"stale primary term {term}")
            self.primary_term = max(self.primary_term, term)
            seq = int(op["seq_no"])
            encoded = self.translog.encode(op)
            self.translog.add_encoded(encoded)
            self._replica_ops[seq] = op
            cur = self._version_map.get(op["id"])
            if cur is None or cur.seq_no < seq:
                self._version_map[str(op["id"])] = VersionEntry(
                    seq_no=seq, version=int(op["version"]),
                    deleted=op["op"] == "delete", hot_idx=-1)
                self._doc_terms[str(op["id"])] = term
            self._seq_no = max(self._seq_no, seq)
            self._mark_seq_processed(seq)
            # the primary's view of the global checkpoint rides every
            # replication op (ReplicationOperation piggyback)
            if op.get("global_checkpoint") is not None:
                self.global_checkpoint = max(
                    self.global_checkpoint, int(op["global_checkpoint"]))

    # -- retention leases (index/seqno/RetentionLease.java analog) --------

    def add_retention_lease(self, lease_id: str, retaining_seq_no: int):
        """Primary: retain translog ops from ``retaining_seq_no`` on for
        the lease holder, so a briefly-partitioned replica can recover
        by op replay instead of a full file copy."""
        with self._lock:
            self.retention_leases[str(lease_id)] = int(retaining_seq_no)

    def remove_retention_lease(self, lease_id: str):
        with self._lock:
            self.retention_leases.pop(str(lease_id), None)

    def get_retention_leases(self) -> dict:
        with self._lock:
            return dict(self.retention_leases)

    def ops_since(self, from_seq: int):
        """Every op with seq_no > from_seq, in order — or None when the
        translog no longer retains a contiguous history up to the global
        checkpoint (then only a file copy can recover).  Contiguity is
        checked in O(n) over the RETAINED ops (seq_nos are unique), never
        over the full history."""
        from_seq = int(from_seq)
        with self._lock:
            self._ensure_open()
            ops = sorted({op["seq_no"]: op
                          for op in self.translog.read_ops(from_seq)
                          }.values(), key=lambda o: o["seq_no"])
            expected = self._seq_no - from_seq
            if (len(ops) == expected
                    and (expected == 0
                         or (ops[0]["seq_no"] == from_seq + 1
                             and ops[-1]["seq_no"] == self._seq_no))):
                return ops
            return None

    def checkpoint_info(self) -> dict:
        """Current segment-set checkpoint the primary publishes after a
        refresh (ReplicationCheckpoint analog): segment ids + per-segment
        live bitmaps (deletes travel with the checkpoint) + seq/term."""
        with self._lock:
            self._ensure_open()
            return {"segments": [s.seg_id for s in self.segments],
                    "live": {s.seg_id: s.live.tobytes()
                             for s in self.segments},
                    "max_seq_no": self._seq_no,
                    "primary_term": self.primary_term,
                    # per-doc terms ride the checkpoint so replica and
                    # search-tier digests stay term-comparable (term 1
                    # is implicit)
                    "doc_terms": {k: v for k, v in self._doc_terms.items()
                                  if v > 1}}

    def segments_blobs(self, seg_ids: list) -> dict:
        """Serialize the requested segments for wire copy (recovery
        phase-1 / segrep file transfer)."""
        with self._lock:
            self._ensure_open()
            by_id = {s.seg_id: s for s in self.segments}
            return {sid: segment_to_blobs(by_id[sid]) for sid in seg_ids
                    if sid in by_id}

    def install_checkpoint(self, ckpt: dict, blobs: dict):
        """Replica side: adopt the primary's segment set.  Missing
        segments come from ``blobs``; live bitmaps are overwritten from
        the checkpoint; buffered ops and version-map entries now covered
        by segments are dropped."""
        with self._lock:
            self._ensure_open()
            term = int(ckpt.get("primary_term", 1))
            if term < self.primary_term:
                raise VersionConflictError(
                    "<checkpoint>", f"primary term >= {self.primary_term}",
                    f"stale primary term {term}")
            self.primary_term = term
            have = {s.seg_id: s for s in self.segments}
            new_segments = []
            for sid in ckpt["segments"]:
                seg = have.get(sid)
                if seg is None:
                    seg = segment_from_blobs(blobs[sid])
                live = np.frombuffer(ckpt["live"][sid], dtype=bool)
                if (sid in self._persisted_segments
                        and not np.array_equal(seg.live, live)):
                    # deletes travel with the checkpoint: an already-
                    # persisted segment needs its .liv rewritten on the
                    # next flush or a restart resurrects deleted docs
                    self._live_dirty.add(sid)
                seg.live = live.copy()
                new_segments.append(seg)
            self.segments = new_segments
            covered = int(ckpt["max_seq_no"])
            self._seq_no = max(self._seq_no, covered)
            self._advance_local_ckpt_to(covered)
            for k, v in (ckpt.get("doc_terms") or {}).items():
                self._doc_terms[str(k)] = int(v)
            self._replica_ops = {s: op for s, op in self._replica_ops.items()
                                 if s > covered}
            self._version_map = {k: v for k, v in self._version_map.items()
                                 if v.seq_no > covered}
            self._searcher = None

    def install_remote_checkpoint(self, ckpt: dict,
                                  new_segments: dict):
        """Search-only replica side: adopt a primary-published segment
        set whose missing segments were already materialized from the
        remote store (CRC-verified ``Segment`` objects in
        ``new_segments``).  Unlike ``install_checkpoint`` there is no
        replica op buffer to reconcile — searchers hold no write state
        at all; live bitmaps come from the checkpoint when present
        (push path) or from the segments' own ``.liv`` sidecars (pull /
        recovery path)."""
        with self._lock:
            self._ensure_open()
            term = int(ckpt.get("primary_term", 1))
            if term < self.primary_term:
                raise VersionConflictError(
                    "<checkpoint>", f"primary term >= {self.primary_term}",
                    f"stale primary term {term}")
            self.primary_term = term
            have = {s.seg_id: s for s in self.segments}
            segments = []
            for sid in ckpt["segments"]:
                seg = have.get(sid)
                if seg is None:
                    seg = new_segments[sid]
                live = (ckpt.get("live") or {}).get(sid)
                if live is not None:
                    seg.live = np.frombuffer(live, dtype=bool).copy()
                segments.append(seg)
                # the files backing this segment are on disk (cache
                # links + regenerated manifests): never re-save them
                self._persisted_segments.add(sid)
            self.segments = segments
            self._seq_no = max(self._seq_no, int(ckpt["max_seq_no"]))
            self._advance_local_ckpt_to(int(ckpt["max_seq_no"]))
            for k, v in (ckpt.get("doc_terms") or {}).items():
                self._doc_terms[str(k)] = int(v)
            self._searcher = None

    def promote_to_primary(self, term: int):
        """Replica -> primary on failover: replay buffered (not yet
        segment-covered) ops through the indexing path so they become
        searchable, under the new primary term (the reference's promotion
        + translog replay, ref IndexShard routing-change promotion)."""
        with self._lock:
            self._ensure_open()
            self.primary_term = max(int(term), self.primary_term)
            ops = sorted(self._replica_ops.values(),
                         key=lambda o: o["seq_no"])
            self._replica_ops.clear()
            for op in ops:
                self._version_map.pop(str(op["id"]), None)
            for op in ops:
                self._replay(op)

    def advance_primary_term(self, term: int):
        """Monotonically adopt a (validated) new primary term — the
        replica side of a promotion resync bumps its engine term here
        after replaying the resync ops, which keep their original
        (older) terms."""
        with self._lock:
            self.primary_term = max(self.primary_term, int(term))

    def rollback_above(self, seq: int) -> int:
        """Drop every op with seq_no above ``seq`` from this copy — the
        deposed-primary / divergent-replica rollback (the reference's
        resetEngineToGlobalCheckpoint +
        trimOperationsOfPreviousPrimaryTerms).  Ops above the global
        checkpoint were never acked against a full in-sync set, so
        cancelling them cannot lose an acked write; a doc UPDATED above
        the cut resurrects its newest retained version at or below it.
        Durable: the translog gets a trim marker before in-memory state
        changes, so a restart replays the post-rollback history.
        Returns the number of ops rolled back."""
        with self._lock:
            self._ensure_open()
            seq = int(seq)
            if self._seq_no <= seq:
                return 0
            self.translog.trim_above(seq)
            dropped = len([s for s in self._replica_ops if s > seq])
            self._replica_ops = {s: op for s, op in
                                 self._replica_ops.items() if s <= seq}
            removed: list[str] = []
            for doc_id, e in list(self._version_map.items()):
                if e.seq_no > seq:
                    if e.hot_idx >= 0 and self._hot[e.hot_idx] is not None:
                        self._hot[e.hot_idx] = None
                        dropped += 1
                    del self._version_map[doc_id]
                    self._doc_terms.pop(doc_id, None)
                    removed.append(doc_id)
            # already-refreshed divergent docs: clear their live bits so
            # the newest retained copy (an older segment doc) resurfaces
            for seg in self.segments:
                locals_ = [i for i in range(seg.n_docs)
                           if seg.live[i] and int(seg.seq_nos[i]) > seq]
                if locals_:
                    seg.apply_deletes(locals_)
                    self._live_dirty.add(seg.seg_id)
                    dropped += len(locals_)
            # a rolled-back update/delete queued a tombstone against the
            # doc's OLDER copy — keep it only if a live newer version of
            # that doc still exists, else the old copy must stay live
            kept = []
            for seg, local in self._pending_deletes:
                did = str(seg.doc_ids[local])
                cur = self._current_entry(did)
                if cur is not None and not cur.deleted \
                        and cur.seq_no > int(seg.seq_nos[local]):
                    kept.append((seg, local))
            self._pending_deletes = kept
            # a doc written twice above+below the cut lost its retained
            # in-memory copy when the second write nulled the first's hot
            # slot — re-apply the newest retained translog op for it
            for doc_id in removed:
                best = None
                for op in self.translog.read_ops(-1):
                    if str(op.get("id")) == doc_id and \
                            (best is None
                             or op["seq_no"] > best["seq_no"]):
                        best = op
                cur = self._current_entry(doc_id)
                if best is not None and (cur is None
                                         or cur.seq_no < best["seq_no"]):
                    self._replay(best)
            self._seq_no = seq
            self._local_ckpt = min(self._local_ckpt, seq)
            self._pending_seqs = {s for s in self._pending_seqs
                                  if s <= seq}
            self._searcher = None
            return dropped

    def replication_digest(self) -> dict:
        """Per-doc ``(seq_no, primary_term, version, content-crc)`` over
        every live doc on this copy, plus rolled-up digests — the
        durability audit's cross-copy parity probe.  ``digest`` covers the
        full tuple; ``seq_digest`` leaves the term out, for search-tier
        copies whose pull-path refill cannot recover per-doc terms."""
        import zlib as _zlib
        with self._lock:
            self._ensure_open()
            ids = set(self._version_map)
            for seg in self.segments:
                ids.update(str(i) for i in seg.id_to_local)
            docs: dict[str, list] = {}
            for doc_id in sorted(ids):
                e = self._version_map.get(doc_id)
                src = None
                if e is not None:
                    if e.deleted:
                        continue
                    if e.hot_idx >= 0:
                        d = self._hot[e.hot_idx]
                        src = d.source if d is not None else None
                    else:
                        rop = self._replica_ops.get(e.seq_no)
                        if rop is not None and str(rop["id"]) == doc_id:
                            src = rop["source"]
                if e is None or src is None:
                    for seg in reversed(self.segments):
                        local = seg.id_to_local.get(doc_id)
                        if local is not None and seg.live[local]:
                            if e is None:
                                e = VersionEntry(
                                    seq_no=int(seg.seq_nos[local]),
                                    version=int(seg.versions[local]),
                                    deleted=False)
                            src = seg.source(local)
                            break
                    if e is None:
                        continue
                crc = 0
                if src is not None:
                    crc = _zlib.crc32(json.dumps(
                        src, sort_keys=True,
                        separators=(",", ":")).encode()) & 0xFFFFFFFF
                docs[doc_id] = [int(e.seq_no),
                                int(self._doc_terms.get(doc_id, 1)),
                                int(e.version), crc]
            blob = json.dumps(sorted(docs.items()),
                              separators=(",", ":")).encode()
            seq_blob = json.dumps(
                sorted((k, [v[0], v[2], v[3]]) for k, v in docs.items()),
                separators=(",", ":")).encode()
            return {"docs": docs,
                    "doc_count": len(docs),
                    "digest": _zlib.crc32(blob) & 0xFFFFFFFF,
                    "seq_digest": _zlib.crc32(seq_blob) & 0xFFFFFFFF}

    # -- read path --------------------------------------------------------

    def get(self, doc_id: str, realtime: bool = True) -> Optional[dict]:
        """Realtime GET via the version map + hot buffer (LiveVersionMap /
        ShardGetService analog); realtime=False reads search-visible state."""
        with self._lock:
            self._ensure_open()
            doc_id = str(doc_id)
            if realtime:
                e = self._version_map.get(doc_id)
                if e is not None:
                    if e.deleted:
                        return None
                    if e.hot_idx >= 0:
                        doc = self._hot[e.hot_idx]
                        out = {"_id": doc_id, "_version": e.version,
                               "_seq_no": e.seq_no,
                               "_primary_term": self.primary_term,
                               "_source": doc.source, "found": True}
                        if doc.routing is not None:
                            out["_routing"] = doc.routing
                        return self._finish_get(out)
                    rop = self._replica_ops.get(e.seq_no)
                    if rop is not None and rop["id"] == doc_id:
                        # replica realtime GET from the buffered op (the
                        # reference reads the translog, ShardGetService)
                        out = {"_id": doc_id, "_version": e.version,
                               "_seq_no": e.seq_no,
                               "_primary_term": self.primary_term,
                               "_source": rop["source"], "found": True}
                        if rop.get("routing") is not None:
                            out["_routing"] = rop["routing"]
                        return self._finish_get(out)
                # falls through: doc lives in a segment
            # pending (unrefreshed) deletes stay visible to non-realtime
            # reads, exactly like an unrefreshed Lucene reader
            for seg in reversed(self.segments):
                local = seg.id_to_local.get(doc_id)
                if local is not None and seg.live[local]:
                    out = {"_id": doc_id,
                           "_version": int(seg.versions[local]),
                           "_seq_no": int(seg.seq_nos[local]),
                           "_primary_term": self.primary_term,
                           "_source": seg.source(local), "found": True}
                    routing = seg.routings.get(local)
                    if routing is not None:
                        out["_routing"] = routing
                    return self._finish_get(out)
            return None

    def _finish_get(self, out: dict) -> dict:
        """_source meta-field policy: enabled=false never returns source
        (SourceFieldMapper.enabled)."""
        if not getattr(self.mapper, "source_enabled", True):
            out.pop("_source", None)
        return out

    def acquire_searcher(self) -> ShardSearcher:
        """Search-visible snapshot; refresh() publishes new segments."""
        with self._lock:
            self._ensure_open()
            if self._searcher is None:
                for seg in self.segments:
                    # ledger-owner attribution: a staged segment reports
                    # who it belongs to in _nodes/stats `device`
                    seg.index_name = self.index_name
                    seg.shard_id = self.shard_id
                self._searcher = ShardSearcher(
                    list(self.segments), self.mapper,
                    index_name=self.index_name, shard_id=self.shard_id)
            return self._searcher

    # -- refresh / flush / merge -----------------------------------------

    def refresh(self) -> int:
        """Publish buffered writes + pending deletes to searchers
        (OpenSearchReaderManager.refresh analog).  Returns the number of
        docs in the new segment (0 if none was created)."""
        from opensearch_tpu.common.telemetry import metrics, tracer
        with tracer().start_span(
                "engine.refresh",
                {"index": self.index_name, "shard": self.shard_id}), \
                metrics().time_ms("indexing.refresh_ms"), self._lock:
            self._ensure_open()
            by_seg: dict[int, tuple[Segment, list[int]]] = {}
            for seg, local in self._pending_deletes:
                by_seg.setdefault(id(seg), (seg, []))[1].append(local)
            for seg, locals_ in by_seg.values():
                seg.apply_deletes(locals_)     # copy-on-write live bitmap
                self._live_dirty.add(seg.seg_id)
            self._pending_deletes.clear()
            hot_docs = [d for d in self._hot if d is not None]
            created = 0
            if hot_docs:
                seg_id = f"seg_{self._engine_uid}_{self._seg_counter}"
                self._seg_counter += 1
                seg = self._writer.build(hot_docs, seg_id,
                                         vector_meta=self._vector_meta())
                self.segments.append(seg)
                created = seg.n_docs
            self._hot.clear()
            # entries now resolvable from segments; keep tombstones
            # (deleted-doc versions must survive until trimmed, like the
            # reference's tombstone retention) and entries backed only by
            # the replica op buffer (no local segment holds them until a
            # checkpoint installs)
            self._version_map = {k: v for k, v in self._version_map.items()
                                 if v.deleted
                                 or v.seq_no in self._replica_ops}
            self._searcher = None
            return created

    def _vector_meta(self) -> dict:
        out = {}
        for path, ft in self.mapper.field_types().items():
            if ft.dv_kind == "vector":
                out[path] = {"dims": ft.dims,
                             "similarity": getattr(ft, "space_type", "l2")}
        return out

    def flush(self) -> dict:
        """refresh + persist segments + commit point + translog trim
        (InternalEngine.flush -> Lucene commit analog)."""
        with self._lock:
            self._ensure_open()
            self.refresh()
            seg_dir = os.path.join(self.data_path, "segments")
            for seg in self.segments:
                if seg.seg_id not in self._persisted_segments:
                    save_segment(seg, seg_dir, codec=self.codec)
                    self._persisted_segments.add(seg.seg_id)
                elif seg.seg_id in self._live_dirty:
                    save_live(seg, seg_dir)
            self._live_dirty.clear()
            self._gen_max_seq[self.translog.generation] = self._seq_no
            self.translog.roll_generation()
            commit = {"segments": [s.seg_id for s in self.segments],
                      "max_seq_no": self._seq_no,
                      "seg_counter": self._seg_counter,
                      "translog_generation": self.translog.generation,
                      "primary_term": self.primary_term,
                      # per-doc terms survive restart so the durability
                      # digest stays copy-comparable (term 1 implicit)
                      "doc_terms": {k: v for k, v in
                                    self._doc_terms.items() if v > 1}}
            tmp = os.path.join(self.data_path, self.COMMIT_FILE + ".tmp")
            with open(tmp, "w") as f:
                json.dump(commit, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(self.data_path, self.COMMIT_FILE))
            if not self.retention_leases:
                self.translog.trim(self.translog.generation)
                self._gen_max_seq.clear()
            else:
                # trim only the generations EVERY lease has moved past:
                # history stays bounded by the slowest replica's
                # checkpoint, not unbounded (RetentionLease semantics)
                floor = min(self.retention_leases.values())
                keep = self.translog.generation
                for gen in sorted(self._gen_max_seq):
                    if self._gen_max_seq[gen] > floor:
                        keep = min(keep, gen)
                        break
                self.translog.trim(keep)
                for gen in [g for g in self._gen_max_seq if g < keep]:
                    del self._gen_max_seq[gen]
            # Delete tombstones at or below the committed max seq-no are
            # durable in the persisted live bitmaps now — prune them so a
            # delete-heavy workload doesn't grow the version map forever
            # (the reference's GC-deletes keyed on checkpoint advancement).
            committed_seq = commit["max_seq_no"]
            # ...but never prune a tombstone still backed only by the
            # replica op buffer: until a checkpoint installs, no local
            # segment live-bitmap reflects the delete, and dropping the
            # entry would let a replica realtime GET resurrect the doc
            # from an older installed segment (mirrors refresh() above).
            self._version_map = {
                k: v for k, v in self._version_map.items()
                if not (v.deleted and v.seq_no <= committed_seq
                        and v.seq_no not in self._replica_ops)}
            # the new commit no longer references merged-away segments —
            # their files are safe to delete now
            for seg_id in self._obsolete_files:
                delete_segment_files(seg_dir, seg_id)
            self._obsolete_files.clear()
            return commit

    def force_merge(self, max_num_segments: int = 1) -> int:
        """Rewrite live docs into ``max_num_segments`` fresh segments
        (OpenSearchTieredMergePolicy's forced path; renumbers docs like a
        Lucene merge)."""
        with self._lock:
            self._ensure_open()
            self.refresh()
            if len(self.segments) <= max_num_segments:
                return len(self.segments)
            live_docs = []
            for seg in self.segments:
                for local in range(seg.n_docs):
                    if seg.live[local]:
                        doc = self.mapper.parse(seg.doc_ids[local],
                                                seg.source(local),
                                                routing=seg.routings.get(
                                                    local))
                        doc.seq_no = int(seg.seq_nos[local])
                        doc.version = int(seg.versions[local])
                        live_docs.append(doc)
            old = self.segments
            self.segments = []
            if live_docs:
                per = max(1, -(-len(live_docs) // max_num_segments))
                for i in range(0, len(live_docs), per):
                    seg_id = f"seg_{self._engine_uid}_{self._seg_counter}"
                    self._seg_counter += 1
                    self.segments.append(self._writer.build(
                        live_docs[i: i + per], seg_id,
                        vector_meta=self._vector_meta()))
            for seg in old:
                if seg.seg_id in self._persisted_segments:
                    # defer file deletion until the next commit point no
                    # longer references them (crash-safe)
                    self._obsolete_files.add(seg.seg_id)
                    self._persisted_segments.discard(seg.seg_id)
                self._live_dirty.discard(seg.seg_id)
            self._searcher = None
            return len(self.segments)

    # -- stats ------------------------------------------------------------

    def doc_count(self) -> int:
        with self._lock:
            n = sum(1 for d in self._hot if d is not None)
            vm_deleted = 0
            n += sum(s.live_count() for s in self.segments)
            for seg, local in self._pending_deletes:
                if seg.live[local]:
                    vm_deleted += 1
            return n - vm_deleted

    @property
    def max_seq_no(self) -> int:
        return self._seq_no

    def stats(self) -> dict:
        with self._lock:
            return {
                "docs": {"count": self.doc_count()},
                "segments": {"count": len(self.segments)},
                "seq_no": {"max_seq_no": self._seq_no,
                           "local_checkpoint": self._local_ckpt,
                           "global_checkpoint": self.global_checkpoint,
                           "primary_term": self.primary_term},
                "translog": {"generation": self.translog.generation},
            }
