from opensearch_tpu.index.segment import Segment, SegmentWriter  # noqa: F401
