"""Ingest pipelines: document transforms applied before indexing.

Analog of the reference's IngestService + the ingest-common module's
processors (ref ingest/IngestService.java:560,578,728 executePipelines;
modules/ingest-common 4.7k LoC).  A pipeline is a named list of
processors run host-side over the raw source dict — ingest never touches
the device path, exactly like the reference runs it on the coordinating
node before the engine sees the doc.

Processors: set, remove, rename, convert, lowercase, uppercase, trim,
split, join, append, gsub, date, fail, drop.  Each supports
``ignore_missing`` where the reference does, ``on_failure`` handlers,
and ``ignore_failure``.  Field paths are dotted; ``{{field}}`` mustache
templates resolve in ``set``'s value and ``fail``'s message.
"""

from __future__ import annotations

import datetime as _dt
import json
import os
import re
import threading
from typing import Any, Callable, Optional

from opensearch_tpu.common.errors import (IllegalArgumentError,
                                          OpenSearchTpuError,
                                          ResourceNotFoundError)


class IngestProcessorError(OpenSearchTpuError):
    status = 400


class DropDocument(Exception):
    """Control-flow: the drop processor removes the doc from the batch."""


# -- dotted-path helpers ------------------------------------------------------


def path_get(doc: dict, path: str, default=None):
    cur: Any = doc
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return default
        cur = cur[part]
    return cur


def path_has(doc: dict, path: str) -> bool:
    sentinel = object()
    return path_get(doc, path, sentinel) is not sentinel


def path_set(doc: dict, path: str, value):
    parts = path.split(".")
    cur = doc
    for part in parts[:-1]:
        nxt = cur.get(part)
        if not isinstance(nxt, dict):
            nxt = cur[part] = {}
        cur = nxt
    cur[parts[-1]] = value


_MISSING = object()


def path_del(doc: dict, path: str) -> bool:
    parts = path.split(".")
    cur = doc
    for part in parts[:-1]:
        cur = cur.get(part)
        if not isinstance(cur, dict):
            return False
    if not isinstance(cur, dict):
        return False
    # sentinel: a present-but-null field still counts as deleted
    return cur.pop(parts[-1], _MISSING) is not _MISSING


_TEMPLATE = re.compile(r"\{\{\s*([\w.]+)\s*\}\}")


def render_template(value, doc: dict):
    """{{field}} mustache substitution against the document."""
    if not isinstance(value, str) or "{{" not in value:
        return value
    return _TEMPLATE.sub(
        lambda m: str(path_get(doc, m.group(1), "")), value)


# -- processors ---------------------------------------------------------------


def _p_set(conf):
    field = conf["field"]
    value = conf.get("value")
    override = conf.get("override", True)

    def run(doc):
        if not override and path_has(doc, field):
            return
        path_set(doc, field, render_template(value, doc))
    return run


def _p_remove(conf):
    fields = conf["field"]
    if not isinstance(fields, list):
        fields = [fields]
    ignore_missing = conf.get("ignore_missing", False)

    def run(doc):
        for f in fields:
            if not path_del(doc, f) and not ignore_missing:
                raise IngestProcessorError(f"field [{f}] not present")
    return run


def _p_rename(conf):
    field, target = conf["field"], conf["target_field"]
    ignore_missing = conf.get("ignore_missing", False)

    def run(doc):
        if not path_has(doc, field):
            if ignore_missing:
                return
            raise IngestProcessorError(f"field [{field}] not present")
        if path_has(doc, target):
            raise IngestProcessorError(
                f"field [{target}] already exists")
        path_set(doc, target, path_get(doc, field))
        path_del(doc, field)
    return run


def _p_convert(conf):
    field = conf["field"]
    target = conf.get("target_field", field)
    typ = conf["type"]
    ignore_missing = conf.get("ignore_missing", False)
    converters: dict[str, Callable] = {
        "integer": int, "long": int, "float": float, "double": float,
        "string": str,
        "boolean": lambda v: (v if isinstance(v, bool) else
                              str(v).lower() == "true"),
        "auto": lambda v: _auto_convert(v),
    }
    if typ not in converters:
        raise IllegalArgumentError(f"[convert] unknown type [{typ}]")

    def run(doc):
        if not path_has(doc, field):
            if ignore_missing:
                return
            raise IngestProcessorError(f"field [{field}] not present")
        v = path_get(doc, field)
        try:
            if isinstance(v, list):
                out = [converters[typ](x) for x in v]
            else:
                out = converters[typ](v)
        except (TypeError, ValueError) as e:
            raise IngestProcessorError(
                f"[convert] cannot convert [{v!r}] to {typ}: {e}") \
                from None
        path_set(doc, target, out)
    return run


def _auto_convert(v):
    if isinstance(v, str):
        for fn in (int, float):
            try:
                return fn(v)
            except ValueError:
                pass
        if v.lower() in ("true", "false"):
            return v.lower() == "true"
    return v


def _string_proc(fn):
    def build(conf):
        field = conf["field"]
        target = conf.get("target_field", field)
        ignore_missing = conf.get("ignore_missing", False)

        def run(doc):
            if not path_has(doc, field):
                if ignore_missing:
                    return
                raise IngestProcessorError(f"field [{field}] not present")
            v = path_get(doc, field)
            if isinstance(v, list):
                path_set(doc, target, [fn(str(x)) for x in v])
            else:
                path_set(doc, target, fn(str(v)))
        return run
    return build


def _p_split(conf):
    field = conf["field"]
    sep = _compile_rx(conf["separator"], "split")
    target = conf.get("target_field", field)
    ignore_missing = conf.get("ignore_missing", False)

    def run(doc):
        if not path_has(doc, field):
            if ignore_missing:
                return
            raise IngestProcessorError(f"field [{field}] not present")
        path_set(doc, target, sep.split(str(path_get(doc, field))))
    return run


def _p_join(conf):
    field = conf["field"]
    sep = conf["separator"]
    target = conf.get("target_field", field)

    def run(doc):
        v = path_get(doc, field)
        if not isinstance(v, list):
            raise IngestProcessorError(
                f"[join] field [{field}] is not an array")
        path_set(doc, target, sep.join(str(x) for x in v))
    return run


def _p_append(conf):
    field = conf["field"]
    value = conf.get("value")

    def run(doc):
        cur = path_get(doc, field)
        add = value if isinstance(value, list) else [value]
        add = [render_template(v, doc) for v in add]
        if cur is None:
            path_set(doc, field, list(add))
        elif isinstance(cur, list):
            cur.extend(add)
        else:
            path_set(doc, field, [cur, *add])
    return run


def _compile_rx(pattern: str, proc: str):
    try:
        return re.compile(pattern)
    except re.error as e:
        raise IllegalArgumentError(
            f"[{proc}] invalid pattern [{pattern}]: {e}") from None


def _p_gsub(conf):
    field = conf["field"]
    pattern = _compile_rx(conf["pattern"], "gsub")
    replacement = conf["replacement"]
    target = conf.get("target_field", field)
    ignore_missing = conf.get("ignore_missing", False)

    def run(doc):
        if not path_has(doc, field):
            if ignore_missing:
                return
            raise IngestProcessorError(f"field [{field}] not present")
        path_set(doc, target,
                 pattern.sub(replacement, str(path_get(doc, field))))
    return run


_DATE_FORMATS = {
    "ISO8601": None,                       # handled by fromisoformat
    "UNIX": "unix", "UNIX_MS": "unix_ms",
}


def _p_date(conf):
    field = conf["field"]
    target = conf.get("target_field", "@timestamp")
    formats = conf.get("formats") or ["ISO8601"]

    def run(doc):
        v = path_get(doc, field)
        if v is None:
            raise IngestProcessorError(f"field [{field}] not present")
        for fmt in formats:
            try:
                if fmt == "ISO8601":
                    s = str(v).replace("Z", "+00:00")
                    dt = _dt.datetime.fromisoformat(s)
                elif fmt == "UNIX":
                    dt = _dt.datetime.fromtimestamp(
                        float(v), tz=_dt.timezone.utc)
                elif fmt == "UNIX_MS":
                    dt = _dt.datetime.fromtimestamp(
                        float(v) / 1000.0, tz=_dt.timezone.utc)
                else:
                    dt = _dt.datetime.strptime(str(v), fmt)
                if dt.tzinfo is None:
                    dt = dt.replace(tzinfo=_dt.timezone.utc)
                path_set(doc, target,
                         dt.astimezone(_dt.timezone.utc)
                         .strftime("%Y-%m-%dT%H:%M:%S.%f")[:-3] + "Z")
                return
            except (ValueError, OverflowError):
                continue
        raise IngestProcessorError(
            f"[date] unable to parse [{v!r}] with formats {formats}")
    return run


def _p_fail(conf):
    message = conf.get("message", "Fail processor executed")

    def run(doc):
        raise IngestProcessorError(render_template(message, doc))
    return run


def _p_drop(conf):
    def run(doc):
        raise DropDocument()
    return run


PROCESSORS: dict[str, Callable[[dict], Callable]] = {
    "set": _p_set,
    "remove": _p_remove,
    "rename": _p_rename,
    "convert": _p_convert,
    "lowercase": _string_proc(str.lower),
    "uppercase": _string_proc(str.upper),
    "trim": _string_proc(str.strip),
    "split": _p_split,
    "join": _p_join,
    "append": _p_append,
    "gsub": _p_gsub,
    "date": _p_date,
    "fail": _p_fail,
    "drop": _p_drop,
}

_META_KEYS = ("tag", "description", "if", "ignore_failure", "on_failure")


class Pipeline:
    def __init__(self, pipeline_id: str, body: dict):
        self.id = pipeline_id
        self.description = body.get("description", "")
        self.steps: list[tuple[Callable, dict]] = []
        for entry in body.get("processors") or []:
            if not isinstance(entry, dict) or len(
                    [k for k in entry if k not in _META_KEYS]) != 1:
                raise IllegalArgumentError(
                    "each processor entry must name exactly one "
                    "processor type")
            ((name, conf),) = ((k, v) for k, v in entry.items()
                               if k not in _META_KEYS)
            factory = PROCESSORS.get(name)
            if factory is None:
                raise IllegalArgumentError(
                    f"No processor type exists with name [{name}]")
            conf = dict(conf or {})
            # meta keys (tag/on_failure/...) live INSIDE the processor
            # config in the reference's shape; entry level also accepted
            meta = {k: conf.pop(k) for k in _META_KEYS if k in conf}
            meta.update({k: entry[k] for k in _META_KEYS if k in entry})
            if meta.get("if") is not None:
                raise IllegalArgumentError(
                    "processor [if] conditions (painless) are not "
                    "supported — split into separate pipelines")
            if meta.get("on_failure") is not None:
                # compile handlers ONCE, validating at PUT time
                meta["on_failure_steps"] = Pipeline(
                    "__on_failure__",
                    {"processors": meta["on_failure"]}).steps
            try:
                self.steps.append((factory(conf), meta))
            except KeyError as e:
                raise IllegalArgumentError(
                    f"[{name}] missing required property {e}") from None

    def run(self, doc: dict) -> Optional[dict]:
        """Transform in place; returns None when the doc was dropped."""
        for fn, meta in self.steps:
            try:
                fn(doc)
            except DropDocument:
                return None
            except OpenSearchTpuError as e:
                handlers = meta.get("on_failure_steps")
                if handlers:
                    doc.setdefault("_ingest", {})["on_failure_message"] = \
                        e.reason
                    for h in handlers:
                        try:
                            h[0](doc)
                        except DropDocument:
                            return None    # drop-on-failure pattern
                elif not meta.get("ignore_failure"):
                    raise
        return doc


class IngestService:
    """Named-pipeline registry with on-disk persistence."""

    def __init__(self, data_path: str):
        self._file = os.path.join(data_path, "ingest_pipelines.json")
        self._lock = threading.Lock()
        self._bodies: dict[str, dict] = {}
        self._compiled: dict[str, Pipeline] = {}
        if os.path.exists(self._file):
            with open(self._file) as f:
                self._bodies = json.load(f)
            for pid, body in self._bodies.items():
                self._compiled[pid] = Pipeline(pid, body)

    def _persist(self):
        tmp = self._file + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._bodies, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._file)

    def put(self, pipeline_id: str, body: dict) -> dict:
        compiled = Pipeline(pipeline_id, body)   # validates eagerly
        with self._lock:
            self._bodies[pipeline_id] = body
            self._compiled[pipeline_id] = compiled
            self._persist()
        return {"acknowledged": True}

    def get(self, pipeline_id: Optional[str] = None) -> dict:
        with self._lock:
            if pipeline_id is None:
                return dict(self._bodies)
            if pipeline_id not in self._bodies:
                raise ResourceNotFoundError(
                    f"pipeline with id [{pipeline_id}] does not exist")
            return {pipeline_id: self._bodies[pipeline_id]}

    def delete(self, pipeline_id: str) -> dict:
        with self._lock:
            if pipeline_id not in self._bodies:
                raise ResourceNotFoundError(
                    f"pipeline with id [{pipeline_id}] does not exist")
            del self._bodies[pipeline_id]
            del self._compiled[pipeline_id]
            self._persist()
        return {"acknowledged": True}

    def pipeline(self, pipeline_id: str) -> Pipeline:
        with self._lock:
            p = self._compiled.get(pipeline_id)
        if p is None:
            raise ResourceNotFoundError(
                f"pipeline with id [{pipeline_id}] does not exist")
        return p

    def process(self, pipeline_id: str, source: dict) -> Optional[dict]:
        """Run one doc through a named pipeline (IngestService
        .executePipelines per-doc step); None = dropped."""
        doc = json.loads(json.dumps(source))    # isolated deep copy
        out = self.pipeline(pipeline_id).run(doc)
        if out is not None:
            out.pop("_ingest", None)
        return out

    def simulate(self, pipeline_body: dict, docs: list) -> dict:
        pipeline = Pipeline("_simulate", pipeline_body)
        out = []
        for d in docs or []:
            src = json.loads(json.dumps(d.get("_source") or {}))
            try:
                result = pipeline.run(src)
            except OpenSearchTpuError as e:
                out.append({"error": {"type": e.error_type,
                                      "reason": e.reason}})
                continue
            if result is None:
                out.append({"doc": None})
            else:
                result.pop("_ingest", None)
                out.append({"doc": {"_source": result}})
        return {"docs": out}
