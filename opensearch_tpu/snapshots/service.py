"""Snapshots: incremental, segment-file-level backup into a blob store,
and restore into fresh indices.

Analog of the reference's SnapshotsService + BlobStoreRepository (ref
snapshots/SnapshotsService.java:262 createSnapshot,
snapshots/SnapshotShardsService.java:91 per-shard upload,
repositories/blobstore/BlobStoreRepository.java:1 the index-N/shard-gen
layout, snapshots/RestoreService.java restore).  Immutable array
segments make the incremental story trivial: a segment file's content
hash IS its identity, so unchanged segments across snapshots share one
blob (the reference dedups by file checksum the same way).

Repository layout (content-addressed):

- ``index.json``                 — repository generation: list of snapshots
- ``snap/<name>.json``           — one snapshot's manifest: per index the
                                   settings + mappings + per-shard file
                                   list (logical name -> blob hash)
- ``blobs/<sha256>``             — segment file contents, deduplicated

A snapshot flushes every local shard first, so the captured commit point
covers every acked write (translog is empty at the commit, exactly like
the reference's flush-before-snapshot).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

from opensearch_tpu.common.blobstore import (BLOBSTORE_TYPES, BlobStore,
                                             NoSuchBlobError)
from opensearch_tpu.common.errors import (IllegalArgumentError,
                                          OpenSearchTpuError,
                                          ValidationError)


class RepositoryMissingError(OpenSearchTpuError):
    status = 404


class SnapshotMissingError(OpenSearchTpuError):
    status = 404


class SnapshotInProgressError(OpenSearchTpuError):
    status = 503


class InvalidSnapshotNameError(ValidationError):
    pass


class SnapshotRestoreError(OpenSearchTpuError):
    """A snapshot blob failed content verification on restore — the
    repository bit-rotted under us (the reference's
    SnapshotRestoreException over a CorruptedFileException): the bad
    blob is NAMED and nothing of it is installed."""

    wire_name = "snapshot_restore_exception"
    status = 500


def collect_referenced_blobs(repo, snapshots: Optional[list] = None) -> set:
    """Every blob hash ANY consumer of the shared content-addressed space
    still needs: snapshot manifests AND remote-store shard manifests.
    The GC in delete_snapshot/remote cleanup must use this — collecting
    from snapshots alone would destroy remote-store survivor copies."""
    referenced: set = set()
    if snapshots is None:
        snapshots = repo.list_snapshots()
    for s in snapshots:
        m = repo.manifest(s["snapshot"])
        for imeta in m["indices"].values():
            for smeta in imeta["shards"].values():
                referenced.update(f["blob"] for f in smeta["files"])
    remote_root = repo.store.container("remote")
    for index_name in remote_root.list_children():
        index_c = remote_root.child(index_name)
        for shard_name in index_c.list_children():
            try:
                manifest = json.loads(
                    index_c.child(shard_name).read_blob("manifest.json"))
            except Exception:       # noqa: BLE001 — skip torn manifests
                continue
            referenced.update(f["blob"] for f in manifest["files"])
    return referenced


class Repository:
    def __init__(self, name: str, type_: str, settings: dict):
        factory = BLOBSTORE_TYPES.get(type_)
        if factory is None:
            raise IllegalArgumentError(
                f"repository type [{type_}] not supported — available: "
                f"{sorted(BLOBSTORE_TYPES)}")
        self.name = name
        self.type = type_
        self.settings = settings
        self.store: BlobStore = factory(settings)
        self.root = self.store.container()
        self.snaps = self.store.container("snap")
        self.blobs = self.store.container("blobs")

    # -- repository index --------------------------------------------------

    def list_snapshots(self) -> list[dict]:
        try:
            return json.loads(self.root.read_blob("index.json"))["snapshots"]
        except NoSuchBlobError:
            return []

    def _write_index(self, snapshots: list[dict]):
        self.root.write_blob("index.json",
                             json.dumps({"snapshots": snapshots}).encode())

    def manifest(self, snapshot: str) -> dict:
        try:
            return json.loads(self.snaps.read_blob(snapshot + ".json"))
        except NoSuchBlobError:
            raise SnapshotMissingError(
                f"[{self.name}:{snapshot}] is missing") from None


class SnapshotsService:
    """Node-level snapshot/restore orchestration over registered
    repositories.  ``indices_service`` is the node's IndicesService."""

    def __init__(self, indices_service, data_path: str,
                 path_repo: Optional[list] = None):
        self.indices_service = indices_service
        self.data_path = data_path
        # fs repositories may only live under these roots (the reference
        # rejects locations outside path.repo —
        # FsRepository/Environment.resolveRepoFile); default: the node's
        # own data path
        self.path_repo = [os.path.realpath(p)
                          for p in (path_repo or [data_path])]
        self._repos: dict[str, Repository] = {}
        self._lock = threading.Lock()
        self._in_progress: set[str] = set()
        # serializes every mutation of one repository (create's blob
        # dedup + index.json RMW, delete's GC): a delete running beside a
        # create could collect blobs the create just deduplicated
        # against, and concurrent creates would lose index.json entries
        # (the reference blocks repo ops on in-progress snapshots too)
        self._repo_mutex: dict[str, threading.Lock] = {}
        self._repo_file = os.path.join(data_path, "repositories.json")
        self._load_repos()

    # -- repositories ------------------------------------------------------

    def _load_repos(self):
        if os.path.exists(self._repo_file):
            with open(self._repo_file) as f:
                for name, spec in json.load(f).items():
                    self._repos[name] = Repository(
                        name, spec["type"], spec.get("settings") or {})

    def _persist_repos(self):
        tmp = self._repo_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump({r.name: {"type": r.type, "settings": r.settings}
                       for r in self._repos.values()}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._repo_file)

    def put_repository(self, name: str, body: dict) -> dict:
        type_ = body.get("type")
        if not type_:
            raise ValidationError("repository [type] is required")
        settings = body.get("settings") or {}
        if type_ == "fs":
            loc = os.path.realpath(str(settings.get("location") or ""))
            if not any(loc == root or loc.startswith(root + os.sep)
                       for root in self.path_repo):
                from opensearch_tpu.common.errors import (
                    IllegalArgumentError)
                raise IllegalArgumentError(
                    f"location [{settings.get('location')}] doesn't "
                    "match any of the locations specified by path.repo "
                    f"{self.path_repo}")
        repo = Repository(name, type_, settings)
        # verify: a write+read round trip (VerifyRepositoryAction analog)
        probe = f"verify-{int(time.time() * 1000)}"  # wall-clock: unique name
        repo.root.write_blob(probe, b"ok")
        repo.root.delete_blob(probe)
        with self._lock:
            self._repos[name] = repo
            self._persist_repos()
        return {"acknowledged": True}

    def get_repository(self, name: Optional[str] = None) -> dict:
        with self._lock:
            if name is None:
                return {r.name: {"type": r.type, "settings": r.settings}
                        for r in self._repos.values()}
            repo = self._repos.get(name)
            if repo is None:
                raise RepositoryMissingError(f"[{name}] missing")
            return {name: {"type": repo.type, "settings": repo.settings}}

    def delete_repository(self, name: str) -> dict:
        with self._lock:
            if name not in self._repos:
                raise RepositoryMissingError(f"[{name}] missing")
            del self._repos[name]
            self._persist_repos()
        return {"acknowledged": True}

    def _repo(self, name: str) -> Repository:
        with self._lock:
            repo = self._repos.get(name)
        if repo is None:
            raise RepositoryMissingError(f"[{name}] missing")
        return repo

    def repo_mutex(self, repo_name: str) -> threading.Lock:
        """Public: EVERY mutation of a repository's shared blob space
        (snapshot create/delete GC, remote-store uploads and cleanup)
        must hold this — unsynchronized writers race the GC into
        deleting just-written blobs."""
        return self._mutex(repo_name)

    def _mutex(self, repo_name: str) -> threading.Lock:
        with self._lock:
            lock = self._repo_mutex.get(repo_name)
            if lock is None:
                lock = self._repo_mutex[repo_name] = threading.Lock()
            return lock

    # -- create ------------------------------------------------------------

    def create_snapshot(self, repo_name: str, snapshot: str,
                        body: Optional[dict] = None) -> dict:
        body = body or {}
        if not snapshot or snapshot != snapshot.lower() or "/" in snapshot:
            raise InvalidSnapshotNameError(
                f"invalid snapshot name [{snapshot}]: must be lowercase "
                "without slashes")
        repo = self._repo(repo_name)
        if any(s["snapshot"] == snapshot for s in repo.list_snapshots()):
            raise InvalidSnapshotNameError(
                f"snapshot with the same name [{snapshot}] already exists")
        key = f"{repo_name}/{snapshot}"
        with self._lock:
            if key in self._in_progress:
                raise SnapshotInProgressError(f"[{key}] already running")
            self._in_progress.add(key)
        try:
            with self._mutex(repo_name):
                return self._do_create(repo, snapshot, body)
        finally:
            with self._lock:
                self._in_progress.discard(key)

    def _index_names(self, expr) -> list[str]:
        if not expr or expr in ("_all", "*"):
            return sorted(self.indices_service.indices)
        if isinstance(expr, str):
            expr = [e.strip() for e in expr.split(",") if e.strip()]
        out = []
        for e in expr:
            out.extend(s.name for s in self.indices_service.resolve(e))
        return sorted(set(out))

    def _do_create(self, repo: Repository, snapshot: str, body: dict) -> dict:
        t0 = time.time()   # wall-clock: start_time is a display timestamp
        t0_mono = time.monotonic()    # duration must not jump with clock
        names = self._index_names(body.get("indices"))
        indices_meta = {}
        total_files = 0
        reused_files = 0
        for name in names:
            svc = self.indices_service.get(name)
            shards_meta = {}
            for shard_id, engine in sorted(svc.local_shards.items()):
                commit = engine.flush()
                from opensearch_tpu.index.remote_store import \
                    upload_segment_files
                files, uploaded, reused = upload_segment_files(
                    repo, os.path.join(engine.data_path, "segments"),
                    commit["segments"], strict=False)
                total_files += len(files)
                reused_files += reused
                shards_meta[str(shard_id)] = {
                    "commit": commit, "files": files}
            indices_meta[name] = {
                "settings": dict(svc.settings),
                "mappings": svc.mapper.to_mapping(),
                "shards": shards_meta,
            }
        duration_ms = int((time.monotonic() - t0_mono) * 1000)
        manifest = {
            "snapshot": snapshot,
            "state": "SUCCESS",
            "indices": indices_meta,
            "start_time_in_millis": int(t0 * 1000),
            # end = start + monotonic duration: elapsed stays correct
            # even when the wall clock steps mid-snapshot
            "end_time_in_millis": int(t0 * 1000) + duration_ms,
            "duration_in_millis": duration_ms,
            "total_files": total_files,
            "reused_files": reused_files,
        }
        repo.snaps.write_blob(snapshot + ".json",
                              json.dumps(manifest).encode())
        snapshots = repo.list_snapshots()
        snapshots.append({"snapshot": snapshot, "state": "SUCCESS",
                          "indices": sorted(indices_meta)})
        repo._write_index(snapshots)
        return {"snapshot": {"snapshot": snapshot, "state": "SUCCESS",
                             "indices": sorted(indices_meta),
                             "shards": {"total": sum(
                                 len(m["shards"])
                                 for m in indices_meta.values()),
                                 "failed": 0}}}

    # -- read --------------------------------------------------------------

    def get_snapshot(self, repo_name: str, snapshot: Optional[str]) -> dict:
        repo = self._repo(repo_name)
        if snapshot in (None, "_all", "*"):
            return {"snapshots": repo.list_snapshots()}
        m = repo.manifest(snapshot)
        out = {"snapshot": m["snapshot"],
               "state": m["state"],
               "indices": sorted(m["indices"]),
               "start_time_in_millis": m["start_time_in_millis"],
               "end_time_in_millis": m["end_time_in_millis"]}
        if "duration_in_millis" in m:    # older manifests predate it
            out["duration_in_millis"] = m["duration_in_millis"]
        return {"snapshots": [out]}

    def delete_snapshot(self, repo_name: str, snapshot: str) -> dict:
        """Remove the snapshot, then garbage-collect blobs no other
        snapshot references (BlobStoreRepository's stale-blob cleanup)."""
        repo = self._repo(repo_name)
        # a snapshot backing a mounted (remote_snapshot) index is live
        # data — deleting it would GC the very blobs searches read
        # (ref RestoreService snapshot-in-use check)
        for svc in self.indices_service.indices.values():
            mount = svc.settings.get("remote_snapshot") or {}
            if (mount.get("repository") == repo_name
                    and mount.get("snapshot") == snapshot):
                raise ValidationError(
                    f"cannot delete snapshot [{snapshot}]: mounted as "
                    f"searchable snapshot index [{svc.name}]")
        with self._mutex(repo_name):
            repo.manifest(snapshot)                   # 404 if absent
            snapshots = [s for s in repo.list_snapshots()
                         if s["snapshot"] != snapshot]
            repo._write_index(snapshots)
            repo.snaps.delete_blob(snapshot + ".json")
            referenced = collect_referenced_blobs(repo, snapshots)
            for blob in list(repo.blobs.list_blobs()):
                if blob not in referenced:
                    repo.blobs.delete_blob(blob)
        return {"acknowledged": True}

    # -- restore -----------------------------------------------------------

    def restore_snapshot(self, repo_name: str, snapshot: str,
                         body: Optional[dict] = None) -> dict:
        """Materialize snapshotted shard commit points into fresh index
        directories, then open them (RestoreService analog; restore into
        an existing index name requires it deleted first, like a closed
        index in the reference)."""
        body = body or {}
        repo = self._repo(repo_name)
        m = repo.manifest(snapshot)
        want = body.get("indices")
        names = (self._restore_names(m, want) if want
                 else sorted(m["indices"]))
        rename_pattern = body.get("rename_pattern")
        rename_replacement = body.get("rename_replacement", "")
        restored = []
        for name in names:
            imeta = m["indices"].get(name)
            if imeta is None:
                raise SnapshotMissingError(
                    f"index [{name}] not in snapshot [{snapshot}]")
            target = name
            if rename_pattern:
                import re
                target = re.sub(rename_pattern, rename_replacement, name)
            # validate the (possibly renamed) target BEFORE any file is
            # written: a malicious rename_replacement must not traverse
            # out of the data path, and an invalid name must not leave
            # orphan shard dirs behind
            self.indices_service.validate_name(target)
            if self.indices_service.exists(target):
                raise ValidationError(
                    f"cannot restore index [{target}] because an open "
                    "index with same name already exists — delete it or "
                    "rename on restore")
            index_path = os.path.join(self.indices_service.data_path,
                                      target)
            # storage_type=remote_snapshot mounts the index: no data is
            # copied, shard dirs get a blob reference list and segment
            # files stream through the node file cache at open (the
            # searchable-snapshots RestoreService path, ref
            # RestoreService.java:233 isRemoteSnapshot / FileCache)
            mounted = body.get("storage_type") == "remote_snapshot"
            for shard_id, smeta in imeta["shards"].items():
                shard_dir = os.path.join(index_path, shard_id)
                seg_dir = os.path.join(shard_dir, "segments")
                os.makedirs(seg_dir, exist_ok=True)
                if mounted:
                    tmp = os.path.join(shard_dir, "remote_ref.json.tmp")
                    with open(tmp, "w") as f:
                        json.dump({"files": [
                            {"name": fm["name"], "blob": fm["blob"]}
                            for fm in smeta["files"]]}, f)
                        f.flush()
                        os.fsync(f.fileno())
                    os.replace(tmp, os.path.join(shard_dir,
                                                 "remote_ref.json"))
                else:
                    # every blob is re-hashed against its content
                    # address before installing: a bit-rotted repository
                    # surfaces as snapshot_restore_exception naming the
                    # blob instead of materializing a corrupt shard
                    from opensearch_tpu.index.remote_store import \
                        install_segment_files
                    install_segment_files(
                        seg_dir, smeta["files"], repo.blobs.read_blob,
                        on_corrupt=lambda fname, blob: SnapshotRestoreError(
                            f"[{repo_name}:{snapshot}] blob [{blob}] for "
                            f"file [{fname}] failed checksum verification "
                            "— refusing to install it"))
                commit = dict(smeta["commit"])
                # the restored translog starts empty at the commit's
                # generation (flush-before-snapshot trimmed it)
                tmp = os.path.join(shard_dir, "commit.json.tmp")
                with open(tmp, "w") as f:
                    json.dump(commit, f)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, os.path.join(shard_dir, "commit.json"))
            open_settings = dict(imeta["settings"])
            if mounted:
                open_settings["remote_snapshot"] = {
                    "repository": repo_name, "snapshot": snapshot}
                # a mounted index carries no local replicas — every
                # node reads the same repository blobs
                open_settings["number_of_replicas"] = 0
            self.indices_service.open_restored(
                target, open_settings, imeta["mappings"])
            restored.append(target)
        return {"snapshot": {"snapshot": snapshot,
                             "indices": restored,
                             "shards": {"failed": 0, "total": sum(
                                 len(m["indices"][n]["shards"])
                                 for n in names)}}}

    @staticmethod
    def _restore_names(m: dict, expr) -> list[str]:
        if isinstance(expr, str):
            expr = [e.strip() for e in expr.split(",") if e.strip()]
        import fnmatch
        out = []
        for e in expr:
            hits = fnmatch.filter(sorted(m["indices"]), e)
            if not hits and "*" not in e:
                raise SnapshotMissingError(
                    f"index [{e}] not in snapshot [{m['snapshot']}]")
            out.extend(hits)
        return sorted(set(out))
