from opensearch_tpu.transport.wire import StreamInput, StreamOutput  # noqa: F401
from opensearch_tpu.transport.service import (  # noqa: F401
    LocalTransport,
    TcpTransport,
    TransportService,
)
