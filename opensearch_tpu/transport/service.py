"""Node-to-node RPC: action registry, request/response correlation,
pluggable transports.

Analog of ``transport/TransportService.java`` (sendRequest :150,
registerRequestHandler :1172) over a TcpHeader-style frame
(transport/TcpHeader.java:47-61: marker + length + requestId + status +
version), with two transports:

- ``TcpTransport``: real sockets (the netty4 analog), length-prefixed
  frames, one reader thread per connection, reconnect-per-send on broken
  pipes;
- ``LocalTransport``: in-process hub for multi-node-in-one-process tests
  with MockTransportService-style drop/delay/disconnect rules (ref
  test/framework .../test/transport/MockTransportService.java).

Payloads are generic-value dicts (wire.py), so every action speaks the
same versioned binary format.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
import uuid
from concurrent.futures import Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as FuturesTimeout
from typing import Callable, Optional

from opensearch_tpu.common.errors import (
    NodeDisconnectedError,
    OpenSearchTpuError,
)
from opensearch_tpu.transport.wire import StreamInput, StreamOutput
from opensearch_tpu.version import TRANSPORT_PROTOCOL_VERSION

MARKER = b"OT"
STATUS_RESPONSE = 0x01
STATUS_ERROR = 0x02
STATUS_COMPRESSED = 0x04      # zlib body (TcpHeader's compressed flag)

HANDSHAKE = "internal:tcp/handshake"
COMPRESS_THRESHOLD = 1024     # bytes; small frames ship raw


class ReceiveTimeoutError(OpenSearchTpuError):
    # 503: the peer may come back — retryable, unlike a true 500
    # (the REST layer surfaces these as service-unavailable)
    status = 503


class RemoteTransportError(OpenSearchTpuError):
    status = 500
    remote_type: "str | None" = None   # error_type raised on the remote side


def encode_frame(req_id: int, status: int, action: str,
                 payload: dict, version: int | None = None) -> bytes:
    """``version`` is the NEGOTIATED protocol version for this peer
    (TransportHandshaker); bodies above COMPRESS_THRESHOLD ship
    zlib-compressed with the header flag set (TcpHeader.java:47-61)."""
    import zlib

    out = StreamOutput()
    out.write_vint(version or TRANSPORT_PROTOCOL_VERSION)
    out.write_string(action)
    out.write_value(payload)
    body = out.bytes()
    if len(body) > COMPRESS_THRESHOLD:
        compressed = zlib.compress(body, 3)
        if len(compressed) < len(body):
            body = compressed
            status |= STATUS_COMPRESSED
    return (MARKER + struct.pack(">IQB", len(body) + 9, req_id, status)
            + body)


def decode_frame(body: bytes, status: int = 0):
    import zlib

    if status & STATUS_COMPRESSED:
        body = zlib.decompress(body)
    inp = StreamInput(body)
    version = inp.read_vint()
    inp.version = version
    action = inp.read_string()
    payload = inp.read_value()
    return version, action, payload


def peek_action(frame: bytes) -> str:
    """Action name of a full wire frame (marker + length prefix included)
    WITHOUT materializing the payload — what the fault-injection rules
    pattern-match on.  Response frames carry the request's action too, so
    rules apply symmetrically to both directions."""
    import zlib

    status = frame[14]
    body = frame[15:]
    if status & STATUS_COMPRESSED:
        body = zlib.decompress(body)
    inp = StreamInput(body)
    inp.read_vint()                      # protocol version
    return inp.read_string()


class TransportService:
    def __init__(self, node_id: str, transport: "Transport"):
        self.node_id = node_id
        self.transport = transport
        self._handlers: dict[str, Callable[[dict], dict]] = {}
        self._pending: dict[int, Future] = {}
        self._req_counter = 0
        self._lock = threading.Lock()
        # target -> negotiated protocol version (TransportHandshaker's
        # per-channel version); populated lazily on first contact
        self._peer_versions: dict[str, int] = {}
        # outbound accounting: (action, target) -> requests sent.  The
        # searcher-tier acceptance criterion ("zero primary-directed
        # RPCs during searcher recovery") is asserted against this
        # ledger; bounded by actions x peers
        self.sent_counts: dict[tuple, int] = {}
        self._executor = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix=f"transport-{node_id}")
        self.register_handler(HANDSHAKE, self._on_handshake)
        transport.bind(self)

    def _on_handshake(self, payload: dict) -> dict:
        theirs = int(payload.get("version", 1))
        if theirs // 100 != TRANSPORT_PROTOCOL_VERSION // 100:
            raise OpenSearchTpuError(
                f"incompatible transport protocol: theirs [{theirs}] vs "
                f"ours [{TRANSPORT_PROTOCOL_VERSION}] (major mismatch)")
        return {"version": TRANSPORT_PROTOCOL_VERSION,
                "node": self.node_id}

    def negotiated_version(self, target: str, timeout: float = 5.0) -> int:
        """Handshake once per peer: both sides speak
        min(local, remote) afterwards; a major-version mismatch refuses
        the connection (TransportHandshaker.java)."""
        v = self._peer_versions.get(target)
        if v is not None:
            return v
        fut = self.submit_request(target, HANDSHAKE,
                                  {"version": TRANSPORT_PROTOCOL_VERSION,
                                   "node": self.node_id})
        try:
            r = fut.result(timeout=timeout)
            theirs = int(r.get("version", 1))
        except RemoteTransportError as e:
            if "no handler" in str(e):
                # legacy peer without the handshake handler: assume the
                # current build's version
                theirs = TRANSPORT_PROTOCOL_VERSION
            else:
                raise  # incompatible peer: surface, don't cache
        except Exception:  # noqa: BLE001 — unreachable peer: don't cache
            theirs = TRANSPORT_PROTOCOL_VERSION
        if theirs // 100 != TRANSPORT_PROTOCOL_VERSION // 100:
            raise OpenSearchTpuError(
                f"incompatible transport protocol with [{target}]: "
                f"theirs [{theirs}] vs ours "
                f"[{TRANSPORT_PROTOCOL_VERSION}]")
        v = min(theirs, TRANSPORT_PROTOCOL_VERSION)
        with self._lock:
            self._peer_versions[target] = v
        return v

    # -- registration -----------------------------------------------------

    def register_handler(self, action: str, fn: Callable[[dict], dict]):
        self._handlers[action] = fn

    # -- outbound ---------------------------------------------------------

    def submit_request(self, target: str, action: str,
                       payload: Optional[dict] = None) -> Future:
        version = (self._peer_versions.get(target)
                   if action != HANDSHAKE else TRANSPORT_PROTOCOL_VERSION)
        payload = dict(payload or {})
        # thread-context propagation (the reference ships the ThreadContext
        # headers — traceparent, X-Opaque-Id — inside every transport
        # request so remote executions attribute and parent correctly)
        hdrs = self._outbound_headers()
        if hdrs:
            payload["__headers__"] = hdrs
        with self._lock:
            self._req_counter += 1
            req_id = self._req_counter
            fut: Future = Future()
            self._pending[req_id] = fut
            key = (action, target)
            self.sent_counts[key] = self.sent_counts.get(key, 0) + 1
        try:
            self.transport.send(self.node_id, target,
                                encode_frame(req_id, 0, action,
                                             payload,
                                             version=version))
        except Exception as e:
            with self._lock:
                self._pending.pop(req_id, None)
            fut.set_exception(
                NodeDisconnectedError(f"[{target}] send failed: {e}"))
        return fut

    def send_request(self, target: str, action: str,
                     payload: Optional[dict] = None,
                     timeout: float = 10.0) -> dict:
        fut = self.submit_request(target, action, payload)
        try:
            return fut.result(timeout=timeout)
        # concurrent.futures.TimeoutError only aliases the builtin from
        # 3.11 — catch both or silently-dropped frames crash the caller
        # instead of mapping to ReceiveTimeoutError
        except (TimeoutError, FuturesTimeout):
            # drop the correlation slot or every lost response leaks one
            with self._lock:
                for req_id, pending in list(self._pending.items()):
                    if pending is fut:
                        del self._pending[req_id]
                        break
            raise ReceiveTimeoutError(
                f"[{target}][{action}] request timed out after {timeout}s")

    def requests_sent(self, action: Optional[str] = None,
                      target: Optional[str] = None) -> int:
        """Outbound request count filtered by action and/or target
        (None = any).  ``action`` matches by prefix so families like
        ``indices:admin/replication/`` can be asserted on at once."""
        with self._lock:
            return sum(
                n for (a, t), n in self.sent_counts.items()
                if (action is None or a.startswith(action))
                and (target is None or t == target))

    # -- inbound ----------------------------------------------------------

    def handle_frame(self, source: str, frame: bytes):
        """Called by the transport with one decoded frame body (after the
        length prefix)."""
        req_id, status = struct.unpack(">QB", frame[:9])
        _version, action, payload = decode_frame(frame[9:], status)
        if status & STATUS_RESPONSE:
            with self._lock:
                fut = self._pending.pop(req_id, None)
            if fut is None:
                return
            if status & STATUS_ERROR:
                err = RemoteTransportError(
                    f"[{source}][{payload.get('action', action)}] "
                    f"{payload.get('type')}: {payload.get('reason')}")
                err.remote_type = payload.get("type")
                fut.set_exception(err)
            else:
                fut.set_result(payload)
            return
        try:
            self._executor.submit(self._run_handler, source, req_id, action,
                                  payload)
        except RuntimeError:
            pass   # executor shut down: frame raced our close()

    @staticmethod
    def _outbound_headers() -> dict:
        from opensearch_tpu.common import tasks as taskmod
        from opensearch_tpu.common.telemetry import tracer

        hdrs: dict = {}
        tracer().inject(hdrs)
        task = taskmod.current()
        if task is not None and task.headers.get("X-Opaque-Id"):
            hdrs["X-Opaque-Id"] = task.headers["X-Opaque-Id"]
        return hdrs

    def _run_handler(self, source: str, req_id: int, action: str,
                     payload: dict):
        from opensearch_tpu.common.telemetry import tracer

        handler = self._handlers.get(action)
        hdrs = payload.pop("__headers__", None) if isinstance(
            payload, dict) else None
        try:
            if handler is None:
                raise OpenSearchTpuError(
                    f"no handler for action [{action}]")
            parent = tracer().extract(hdrs)
            if parent is not None:
                # remote execution joins the caller's trace: a server
                # span per handled request (OTel SpanKind.SERVER analog)
                attrs = {"action": action, "source": source,
                         "node": self.node_id}
                if hdrs.get("X-Opaque-Id"):
                    attrs["x_opaque_id"] = hdrs["X-Opaque-Id"]
                with tracer().start_span(f"transport:{action}",
                                         attributes=attrs, parent=parent):
                    result = handler(payload)
            else:
                result = handler(payload)
            frame = encode_frame(req_id, STATUS_RESPONSE, action,
                                 result or {})
        except OpenSearchTpuError as e:
            frame = encode_frame(req_id, STATUS_RESPONSE | STATUS_ERROR,
                                 action, {"type": e.error_type,
                                          "reason": e.reason,
                                          "action": action})
        except Exception as e:  # noqa: BLE001 — rpc boundary
            frame = encode_frame(req_id, STATUS_RESPONSE | STATUS_ERROR,
                                 action, {"type": "internal_error",
                                          "reason": str(e),
                                          "action": action})
        try:
            self.transport.send(self.node_id, source, frame)
        except Exception:
            pass   # peer vanished; their request will time out

    def close(self):
        self.transport.close(self.node_id)
        self._executor.shutdown(wait=False, cancel_futures=True)
        with self._lock:
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(
                        NodeDisconnectedError("transport closed"))
            self._pending.clear()


class Transport:
    def bind(self, service: TransportService):
        raise NotImplementedError

    def send(self, source: str, target: str, frame: bytes):
        raise NotImplementedError

    def close(self, node_id: str):
        raise NotImplementedError


class Directive:
    """What a hub rule may return: pass the frame along after ``delay``
    seconds, delivered ``copies`` times (0 = silently swallow — the
    drop-without-error variant; raising from the rule keeps meaning
    drop-with-send-error).  ``gate`` is a ``threading.Event`` the
    delivery thread waits on first (bounded) — the deterministic "hold
    this frame until the test says so" stall used by the fault-injection
    harness.  Plain floats still mean delay-only, so old rules keep
    working."""

    __slots__ = ("delay", "copies", "gate")

    def __init__(self, delay: float = 0.0, copies: int = 1, gate=None):
        self.delay = float(delay)
        self.copies = int(copies)
        self.gate = gate


class LocalTransport(Transport):
    """In-process hub: every node's TransportService registers here;
    sends are direct calls on the receiver (on the receiver's executor).
    Rules make it the disruption-testing harness (see
    ``testing/fault_injection.py`` for the first-class API)."""

    class Hub:
        def __init__(self):
            self.nodes: dict[str, TransportService] = {}
            self.rules: list[Callable[[str, str, bytes],
                                      "Optional[float | Directive]"]] = []
            self.lock = threading.Lock()

        def add_rule(self, rule):
            """rule(source, target, frame) -> None=pass, float=delay
            seconds, Directive=delay/duplicate/swallow, raise=drop.
            Returns the rule so callers can ``remove_rule`` it later."""
            with self.lock:
                self.rules.append(rule)
            return rule

        def remove_rule(self, rule) -> bool:
            with self.lock:
                try:
                    self.rules.remove(rule)
                    return True
                except ValueError:
                    return False

        def clear_rules(self):
            with self.lock:
                self.rules.clear()

        def disconnect(self, node_id: str):
            def rule(src, dst, frame):
                if src == node_id or dst == node_id:
                    raise NodeDisconnectedError(f"[{node_id}] partitioned")
            return self.add_rule(rule)

    def __init__(self, hub: "LocalTransport.Hub"):
        self.hub = hub
        self.service: Optional[TransportService] = None

    def bind(self, service: TransportService):
        self.service = service
        with self.hub.lock:
            self.hub.nodes[service.node_id] = service

    def send(self, source: str, target: str, frame: bytes):
        delay = 0.0
        copies = 1
        gates = []
        with self.hub.lock:
            rules = list(self.hub.rules)
        for rule in rules:
            d = rule(source, target, frame)
            if isinstance(d, Directive):
                delay = max(delay, d.delay)
                copies = (0 if 0 in (copies, d.copies)
                          else max(copies, d.copies))
                if d.gate is not None:
                    gates.append(d.gate)
            elif d:
                delay = max(delay, float(d))
        svc = self.hub.nodes.get(target)
        if svc is None:
            raise NodeDisconnectedError(f"unknown node [{target}]")
        if copies == 0:
            return                       # swallowed: caller times out

        def deliver():
            for g in gates:
                g.wait(timeout=30.0)     # fault-injection stall gate
            if delay:
                time.sleep(delay)
            for _ in range(copies):
                svc.handle_frame(source, frame[6:])   # strip marker+len
        threading.Thread(target=deliver, daemon=True,
                         name=f"local-deliver-{source}-{target}").start()

    def close(self, node_id: str):
        with self.hub.lock:
            self.hub.nodes.pop(node_id, None)


class TcpTransport(Transport):
    """Real sockets with the frame format above.  Nodes are addressed as
    host:port; an address book maps node ids to addresses."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.service: Optional[TransportService] = None
        self._server = socket.create_server((host, port))
        self.port = self._server.getsockname()[1]
        self.address_book: dict[str, tuple[str, int]] = {}
        self._conns: dict[str, socket.socket] = {}
        self._lock = threading.Lock()            # guards the maps only
        self._target_locks: dict[str, threading.Lock] = {}
        self._running = True
        # accepted inbound connections + their reader threads, so
        # close() can tear them down instead of leaking daemons
        self._inbound: list[socket.socket] = []
        self._readers: list[threading.Thread] = []
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"tcp-accept-{self.port}")

    def bind(self, service: TransportService):
        self.service = service
        self._accept_thread.start()

    def add_node(self, node_id: str, host: str, port: int):
        self.address_book[node_id] = (host, port)

    # -- server side ------------------------------------------------------

    def _accept_loop(self):
        while self._running:
            try:
                conn, _addr = self._server.accept()
            except OSError:
                return
            t = threading.Thread(target=self._read_loop, args=(conn,),
                                 daemon=True,
                                 name=f"tcp-read-{self.port}")
            with self._lock:
                self._inbound.append(conn)
                self._readers.append(t)
            t.start()

    def _read_loop(self, conn: socket.socket):
        try:
            while self._running:
                header = self._read_exact(conn, 6)
                if header is None or header[:2] != MARKER:
                    return
                (length,) = struct.unpack(">I", header[2:6])
                body = self._read_exact(conn, length)
                if body is None:
                    return
                # frames carry the source node id prefixed by the sender
                inp = StreamInput(body)
                source = inp.read_string()
                self.service.handle_frame(source, body[inp._pos:])
        finally:
            conn.close()

    @staticmethod
    def _read_exact(conn, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    # -- client side ------------------------------------------------------

    def _connect(self, target: str) -> socket.socket:
        addr = self.address_book.get(target)
        if addr is None:
            raise NodeDisconnectedError(f"unknown node [{target}]")
        return socket.create_connection(addr, timeout=5)

    def send(self, source: str, target: str, frame: bytes):
        # re-prefix: MARKER + len(source + original body) + source + body
        body = frame[6:]
        out = StreamOutput()
        out.write_string(source)
        prefixed = out.bytes() + body
        wire = MARKER + struct.pack(">I", len(prefixed)) + prefixed
        # per-target locking: a dead peer's connect timeout must not
        # head-of-line-block traffic to healthy peers
        with self._lock:
            tlock = self._target_locks.setdefault(target, threading.Lock())

        def attempt():
            """(Re)connect if needed and write; a broken pipe drops the
            cached connection and surfaces OSError for the retry loop."""
            with self._lock:
                conn = self._conns.get(target)
            if conn is None:
                conn = self._connect(target)
                with self._lock:
                    self._conns[target] = conn
            try:
                conn.sendall(wire)
            except OSError:
                conn.close()
                with self._lock:
                    self._conns.pop(target, None)
                raise

        from opensearch_tpu.common.retry import (RetryExhaustedError,
                                                 retry_call)
        with tlock:
            try:
                # bounded reconnect-per-send: a first broken pipe (peer
                # restarted, connection idled out) retries with backoff
                # instead of failing the caller outright
                retry_call("tcp.send", attempt, retry_on=(OSError,),
                           max_attempts=3, base_delay=0.05,
                           max_delay=0.5, budget_s=2.0,
                           seed=struct.unpack(">I", wire[2:6])[0])
            except RetryExhaustedError as e:
                raise NodeDisconnectedError(
                    f"[{target}] connection failed: {e.last}") from e.last

    def close(self, node_id: str):
        if not self._running:
            return                       # idempotent
        self._running = False
        try:
            self._server.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns.values()) + list(self._inbound)
            self._conns.clear()
            self._inbound.clear()
            readers = list(self._readers)
            self._readers.clear()
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            conn.close()
        # reader threads exit once their sockets die; join briefly so a
        # stopped node leaves no busy daemons behind
        for t in readers:
            t.join(timeout=1.0)
