"""Versioned binary wire serialization.

Analog of ``libs/core`` ``StreamOutput``/``StreamInput``/``Writeable``
(libs/core/src/main/java/org/opensearch/core/common/io/stream/
Writeable.java:46): length-delimited primitives with vint compression,
UTF-8 strings, and a tagged generic-value encoding that covers the JSON
value domain (the reference's ``writeGenericValue``).  Messages carry a
protocol version so readers can gate fields by version exactly like the
reference's ``if (in.getVersion().onOrAfter(...))`` pattern.
"""

from __future__ import annotations

import struct

from opensearch_tpu.common.errors import OpenSearchTpuError


class WireFormatError(OpenSearchTpuError):
    status = 500


class StreamOutput:
    def __init__(self):
        self._parts: list[bytes] = []

    def bytes(self) -> bytes:
        return b"".join(self._parts)

    def write_byte(self, b: int):
        self._parts.append(bytes([b & 0xFF]))

    def write_vint(self, value: int):
        """Unsigned LEB128 (the reference's writeVInt)."""
        if value < 0:
            raise WireFormatError(f"vint cannot encode negative [{value}]")
        out = bytearray()
        while value >= 0x80:
            out.append((value & 0x7F) | 0x80)
            value >>= 7
        out.append(value)
        self._parts.append(bytes(out))

    def write_zlong(self, value: int):
        """Zigzag-encoded signed long (writeZLong).  Python ints are
        arbitrary precision, so (v << 1) ^ (v >> 63) is non-negative for
        any 64-bit value without masking."""
        self.write_vint((value << 1) ^ (value >> 63))

    def write_long(self, value: int):
        self._parts.append(struct.pack(">q", value))

    def write_double(self, value: float):
        self._parts.append(struct.pack(">d", value))

    def write_bool(self, value: bool):
        self.write_byte(1 if value else 0)

    def write_bytes(self, data: bytes):
        self.write_vint(len(data))
        self._parts.append(data)

    def write_string(self, s: str):
        self.write_bytes(s.encode("utf-8"))

    def write_optional_string(self, s):
        if s is None:
            self.write_bool(False)
        else:
            self.write_bool(True)
            self.write_string(s)

    def write_string_list(self, items):
        self.write_vint(len(items))
        for s in items:
            self.write_string(s)

    # tagged generic value (writeGenericValue analog)

    def write_value(self, v):
        if v is None:
            self.write_byte(0)
        elif isinstance(v, bool):
            self.write_byte(1)
            self.write_bool(v)
        elif isinstance(v, int):
            self.write_byte(2)
            self.write_zlong(v)
        elif isinstance(v, float):
            self.write_byte(3)
            self.write_double(v)
        elif isinstance(v, str):
            self.write_byte(4)
            self.write_string(v)
        elif isinstance(v, bytes):
            self.write_byte(5)
            self.write_bytes(v)
        elif isinstance(v, (list, tuple)):
            self.write_byte(6)
            self.write_vint(len(v))
            for item in v:
                self.write_value(item)
        elif isinstance(v, dict):
            self.write_byte(7)
            self.write_vint(len(v))
            for k, item in v.items():
                self.write_string(str(k))
                self.write_value(item)
        else:
            raise WireFormatError(
                f"cannot serialize value of type [{type(v).__name__}]")


class StreamInput:
    def __init__(self, data: bytes, version: int = 1):
        self._data = data
        self._pos = 0
        self.version = version

    def _take(self, n: int) -> bytes:
        if self._pos + n > len(self._data):
            raise WireFormatError("stream truncated")
        out = self._data[self._pos: self._pos + n]
        self._pos += n
        return out

    def read_byte(self) -> int:
        return self._take(1)[0]

    def read_vint(self) -> int:
        shift = 0
        value = 0
        while True:
            b = self.read_byte()
            value |= (b & 0x7F) << shift
            if not b & 0x80:
                return value
            shift += 7
            if shift > 70:
                raise WireFormatError("vint too long")

    def read_zlong(self) -> int:
        v = self.read_vint()
        return (v >> 1) ^ -(v & 1)

    def read_long(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def read_double(self) -> float:
        return struct.unpack(">d", self._take(8))[0]

    def read_bool(self) -> bool:
        return self.read_byte() != 0

    def read_bytes(self) -> bytes:
        return self._take(self.read_vint())

    def read_string(self) -> str:
        return self.read_bytes().decode("utf-8")

    def read_optional_string(self):
        return self.read_string() if self.read_bool() else None

    def read_string_list(self) -> list[str]:
        return [self.read_string() for _ in range(self.read_vint())]

    def read_value(self):
        tag = self.read_byte()
        if tag == 0:
            return None
        if tag == 1:
            return self.read_bool()
        if tag == 2:
            return self.read_zlong()
        if tag == 3:
            return self.read_double()
        if tag == 4:
            return self.read_string()
        if tag == 5:
            return self.read_bytes()
        if tag == 6:
            return [self.read_value() for _ in range(self.read_vint())]
        if tag == 7:
            return {self.read_string(): self.read_value()
                    for _ in range(self.read_vint())}
        raise WireFormatError(f"unknown value tag [{tag}]")

    def remaining(self) -> int:
        return len(self._data) - self._pos
