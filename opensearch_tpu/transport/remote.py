"""Remote clusters + cross-cluster search (CCS).

Analog of ``transport/RemoteClusterService.java`` +
``TransportSearchAction``'s CCS split (ref TransportSearchAction.java:
440,525): index expressions like ``europe:logs-*`` route the sub-search
to a configured remote cluster over its HTTP endpoint; the coordinator
merges remote hits with local ones exactly like the multi-index merge
(per-cluster scoring, query_then_fetch semantics).  Remotes configure
via the affix settings ``cluster.remote.<alias>.seeds`` (a list of
``host:port``), matching the reference's dynamic remote registry.

The DCN story in SURVEY §2.3: cross-cluster traffic rides the host
control plane (HTTP here, where the reference uses its sniff/proxy
transport), never the device mesh.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request

from opensearch_tpu.common.errors import (IllegalArgumentError,
                                          OpenSearchTpuError)


class RemoteClusterError(OpenSearchTpuError):
    status = 502


class RemoteClusterService:
    def __init__(self, settings_fn):
        """``settings_fn() -> dict`` returning the flat cluster settings
        (live: reads the registry each call, so _cluster/settings
        updates apply immediately like addSettingsUpdateConsumer)."""
        self._settings_fn = settings_fn

    def aliases(self) -> dict[str, list[str]]:
        out: dict[str, list[str]] = {}
        for key, value in self._settings_fn().items():
            parts = key.split(".")
            if (len(parts) == 4 and parts[0] == "cluster"
                    and parts[1] == "remote" and parts[3] == "seeds"):
                seeds = value if isinstance(value, list) else [value]
                if seeds:
                    out[parts[2]] = [str(s) for s in seeds]
        return out

    @staticmethod
    def split_indices(expr: str) -> tuple[list[str], dict[str, str]]:
        """'local1,eu:logs-*' -> (['local1'], {'eu': 'logs-*'}) — the
        RemoteClusterAware grouping."""
        local: list[str] = []
        remote: dict[str, list[str]] = {}
        for part in expr.split(","):
            part = part.strip()
            if not part:
                continue
            if ":" in part:
                alias, _, rest = part.partition(":")
                remote.setdefault(alias, []).append(rest)
            else:
                local.append(part)
        return local, {a: ",".join(es) for a, es in remote.items()}

    def search(self, alias: str, index_expr: str, body: dict,
               timeout: float = 30.0) -> dict:
        seeds = self.aliases().get(alias)
        if not seeds:
            raise IllegalArgumentError(
                f"no such remote cluster: [{alias}]")
        last_err = None
        for seed in seeds:
            url = f"http://{seed}/{index_expr}/_search"
            data = json.dumps(body).encode()
            req = urllib.request.Request(
                url, data=data, method="POST",
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=timeout) as r:
                    return json.loads(r.read())
            except urllib.error.HTTPError as e:
                # the remote ANSWERED with an error: surface it, don't
                # fail over (it would answer the same)
                payload = e.read()
                try:
                    reason = json.loads(payload).get("error")
                except (ValueError, AttributeError):
                    reason = payload[:200]
                raise RemoteClusterError(
                    f"remote [{alias}] search failed: {reason}") from None
            except (urllib.error.URLError, TimeoutError, OSError) as e:
                last_err = e
                continue             # seed unreachable: try the next
        raise RemoteClusterError(
            f"cannot connect to remote cluster [{alias}] "
            f"(seeds {seeds}): {last_err}")
