"""Batched multi-query execution: one device program scores MANY queries.

The reference gets throughput from many concurrent search threads each
running the doc-at-a-time hot loop (ContextIndexSearcher.java:318 under
the ``search`` threadpool).  The TPU equivalent is batching: a [Q, T]
block of term-bag queries is one vmapped gather->score->scatter->top_k
program — a single dispatch amortizes host<->device latency (decisive
when the chip sits behind a tunnel) and keeps the MXU/VPU busy with
wide, regular work instead of Q tiny kernels.

Served via ``ShardSearcher.msearch`` (the ``_msearch`` REST analog, ref
action/search/TransportMultiSearchAction.java): bodies that compile to a
plain scored term-bag (match / term / multi-term OR-AND) take the batched
kernel; anything else falls back to the sequential path per body —
semantics are identical either way (same kernels, same tie-breaks).
"""

from __future__ import annotations

from functools import partial

import numpy as np

import opensearch_tpu.common.jaxenv  # noqa: F401
import jax
import jax.numpy as jnp
from jax import lax

from opensearch_tpu.index.segment import pad_bucket, pad_pow2
from opensearch_tpu.ops import bm25 as bm25_ops

_I32 = np.int32
_F32 = np.float32


@partial(jax.jit, static_argnames=("n_pad", "budget", "k"))
def batch_bm25_topk(offsets, doc_ids, tfs, doc_lens, live,
                    term_ids, term_active, idfs, weights, avgdl, required,
                    *, n_pad: int, budget: int, k: int):
    """Score Q term-bag queries against one segment in one program.

    ``term_ids``/``term_active``/``idfs``/``weights`` are [Q, T];
    ``required`` is [Q] (AND = T, OR = minimum_should_match).  Returns
    (vals [Q, k], idx [Q, k], totals [Q], maxes [Q]).
    """

    def one(tid, act, idf_, w, req):
        scores, count = bm25_ops.bm25_score_count(
            offsets, doc_ids, tfs, doc_lens, tid, act, idf_, w, avgdl,
            n_pad=n_pad, budget=budget, scored=True)
        matched = (count >= req) & live
        key = jnp.where(matched, scores, -jnp.inf)
        vals, idx = lax.top_k(key, k)
        return vals, idx, matched.sum(), jnp.max(key)

    return jax.vmap(one)(term_ids, term_active, idfs, weights, required)


class BatchGroup:
    """Queries sharing (field, k) — batched into one [Q, T] program per
    segment."""

    def __init__(self, field: str, k: int):
        self.field = field
        self.k = k
        self.positions: list[int] = []    # index into the msearch bodies
        self.terms: list[tuple] = []
        self.idfs: list[np.ndarray] = []
        self.weights: list[np.ndarray] = []
        self.required: list[int] = []

    def add(self, pos: int, bind: dict):
        self.positions.append(pos)
        self.terms.append(tuple(bind["terms"]))
        self.idfs.append(np.asarray(bind["idfs"], _F32))
        self.weights.append(np.asarray(bind["weights"], _F32))
        self.required.append(int(bind["required"]))

    def run(self, searcher) -> dict:
        """Execute against every segment; returns {pos: (rows, total,
        max_score)} in the sequential path's row format.

        Within a segment, queries are sub-grouped by their own gather
        budget bucket — one kernel launch per (bucket) — so a query over
        rare terms never pays a hot term's gather budget."""
        Q = len(self.positions)
        t_pad = pad_pow2(max(len(t) for t in self.terms), minimum=1)
        k = self.k
        avgdl = searcher.ctx.field_stats(self.field).avgdl
        # accumulated per (query, segment) DEVICE handles; host-synced once
        from opensearch_tpu.common.tasks import check_current

        acc: list[list] = [[] for _ in range(Q)]   # [(seg_order, v, i, t, m)]
        for seg_order, seg in enumerate(searcher.segments):
            check_current()    # cancellation point per segment program
            dseg = seg.device()
            pf = seg.postings.get(self.field)
            p = dseg.postings.get(self.field)
            if pf is None or p is None:
                continue
            tids = np.zeros((Q, t_pad), _I32)
            active = np.zeros((Q, t_pad), bool)
            idfs = np.zeros((Q, t_pad), _F32)
            weights = np.zeros((Q, t_pad), _F32)
            buckets: dict[int, list[int]] = {}
            for qi, terms in enumerate(self.terms):
                b = 0
                for ti, t in enumerate(terms):
                    tid = pf.term_id(t)
                    if tid >= 0:
                        tids[qi, ti] = tid
                        active[qi, ti] = True
                        b += int(pf.df[tid])
                idfs[qi, : len(terms)] = self.idfs[qi]
                weights[qi, : len(terms)] = self.weights[qi]
                buckets.setdefault(pad_bucket(b), []).append(qi)
            live = searcher.ctx.live_jnp(seg, dseg)
            kk = min(k, dseg.n_pad)
            required = np.asarray(self.required, _I32)
            for budget, qis in buckets.items():
                # pad the batch axis to pow2 buckets — every distinct Q
                # would otherwise be its own XLA program
                q_pad = pad_pow2(len(qis), minimum=8)
                sel = np.zeros(q_pad, np.int64)
                sel[: len(qis)] = qis
                req = required[sel].copy()
                req[len(qis):] = t_pad + 1          # padding rows match nothing
                vals, idx, tot, mx = batch_bm25_topk(
                    p["offsets"], p["doc_ids"], p["tfs"], p["doc_lens"],
                    live, jnp.asarray(tids[sel]), jnp.asarray(active[sel]),
                    jnp.asarray(idfs[sel]), jnp.asarray(weights[sel]),
                    jnp.asarray(np.float32(avgdl)),
                    jnp.asarray(req),
                    n_pad=dseg.n_pad, budget=budget, k=kk)
                for bi, qi in enumerate(qis):
                    acc[qi].append((seg_order, vals[bi], idx[bi],
                                    tot[bi], mx[bi]))
        out = {}
        # ONE host sync region: convert after the full dispatch loop
        for qi, pos in enumerate(self.positions):
            rows_v, rows_s, rows_l = [], [], []
            total = 0
            max_score = -np.inf
            for seg_order, vals, idx, tot, mx in acc[qi]:
                vals, idx = np.asarray(vals), np.asarray(idx)
                keep = vals > -np.inf
                rows_v.append(vals[keep])
                rows_s.append(np.full(int(keep.sum()), seg_order, _I32))
                rows_l.append(idx[keep])
                total += int(tot)
                max_score = max(max_score, float(mx))
            if not rows_v:
                out[pos] = ([], 0, None)
                continue
            v = np.concatenate(rows_v)
            s = np.concatenate(rows_s)
            l = np.concatenate(rows_l)
            order = np.lexsort((l, s, -v))[: self.k]
            rows = [{"seg": int(s[i]), "local": int(l[i]),
                     "score": float(v[i])} for i in order]
            out[pos] = (rows, total,
                        None if max_score == -np.inf else float(max_score))
        return out


def plan_batches(searcher, bodies: list) -> tuple[dict, list]:
    """Partition msearch bodies into batchable groups and a fallback list.

    Returns ({(field, k): BatchGroup}, [positions needing the sequential
    path]).  Batchable = scored term-bag (TermBagPlan) with no sort /
    aggs / min_score / source filtering beyond defaults.
    """
    from opensearch_tpu.search import plan as P
    from opensearch_tpu.search.compiler import compile_query
    from opensearch_tpu.search.query_dsl import parse_query

    groups: dict = {}
    fallback = []
    for pos, body in enumerate(bodies):
        body = body or {}
        if (body.get("sort") is not None or body.get("aggs")
                or body.get("aggregations") or body.get("min_score")
                or body.get("highlight") or body.get("explain")
                or body.get("docvalue_fields") or body.get("fields")
                or int(body.get("from", 0)) != 0):
            fallback.append(pos)
            continue
        try:
            plan, bind = compile_query(parse_query(body.get("query")),
                                       searcher.ctx, scored=True)
        except Exception:
            fallback.append(pos)
            continue
        if not isinstance(plan, P.TermBagPlan) or not plan.scored:
            fallback.append(pos)
            continue
        k = int(body.get("size", 10))
        if k <= 0:
            fallback.append(pos)
            continue
        key = (plan.field, k)
        g = groups.get(key)
        if g is None:
            g = groups[key] = BatchGroup(plan.field, k)
        g.add(pos, bind)
    return groups, fallback
