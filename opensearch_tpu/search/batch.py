"""Batched multi-query execution: one device program scores MANY queries.

The reference gets throughput from many concurrent search threads each
running the doc-at-a-time hot loop (ContextIndexSearcher.java:318 under
the ``search`` threadpool).  The TPU equivalent is batching: a [Q, T]
block of term-bag queries is one vmapped gather->score->scatter->top_k
program — a single dispatch amortizes host<->device latency (decisive
when the chip sits behind a tunnel) and keeps the MXU/VPU busy with
wide, regular work instead of Q tiny kernels.

Served via ``ShardSearcher.msearch`` (the ``_msearch`` REST analog, ref
action/search/TransportMultiSearchAction.java): bodies that compile to a
plain scored term-bag (match / term / multi-term OR-AND) take the batched
kernel; anything else falls back to the sequential path per body —
semantics are identical either way (same kernels, same tie-breaks).
"""

from __future__ import annotations

from functools import partial

import numpy as np

import opensearch_tpu.common.jaxenv  # noqa: F401
import jax
import jax.numpy as jnp
from jax import lax

from opensearch_tpu.index.segment import pad_bucket, pad_pow2
from opensearch_tpu.ops import bm25 as bm25_ops

_I32 = np.int32
_F32 = np.float32


@partial(jax.jit, static_argnames=("n_pad", "budget", "k"))
def batch_bm25_union_topk(offsets, doc_ids, tfs, doc_lens, live,
                          union_tids, union_active, union_idfs,
                          weights, act, required, avgdl,
                          *, n_pad: int, budget: int, k: int):
    """Score Q term-bag queries against one segment in ONE program via
    the union-of-terms formulation.

    The naive vmap (round 4) gathered every query's postings separately,
    so a 64-query batch either compiled one program per budget bucket
    (compile explosion) or paid the heaviest query's gather budget 64
    times (work explosion — the r4 throughput inversion).  Instead:

      1. gather the postings of the ~T DISTINCT terms of the whole batch
         once (``budget`` >= sum of their dfs — each posting touched once
         per batch, not once per query);
      2. scatter per-posting BM25 base scores idf*tf/(tf+norm) into a
         dense [n_pad, T] doc x term matrix;
      3. one [Q,T] @ [T,n_pad] matmul applies every query's term weights
         — exactly the shape the MXU wants — and a second matmul over the
         presence matrix counts matched terms for AND /
         minimum_should_match semantics;
      4. batched ``lax.top_k`` over [Q, n_pad].

    ``union_tids``/``union_active``/``union_idfs`` are [T]; ``weights``
    (boost-scaled, accumulated over duplicate query terms) and ``act``
    (occurrence counts, so duplicated terms still satisfy AND) are
    [Q, T]; ``required`` is [Q].  Returns (vals [Q, k], idx [Q, k],
    totals [Q], maxes [Q]).
    """
    d, tf, slot, valid = bm25_ops.gather_postings(
        offsets, doc_ids, tfs, union_tids, union_active,
        budget=budget, pad_doc=n_pad - 1)
    dl = doc_lens[d]
    norm = bm25_ops.K1_DEFAULT * (1.0 - bm25_ops.B_DEFAULT
                                  + bm25_ops.B_DEFAULT * dl / avgdl)
    base = union_idfs[slot] * tf / (tf + norm)
    t_pad = union_tids.shape[0]
    dense = jnp.zeros((n_pad, t_pad), jnp.float32).at[d, slot].add(
        jnp.where(valid, base, 0.0))
    pres = jnp.zeros((n_pad, t_pad), jnp.float32).at[d, slot].add(
        jnp.where(valid, 1.0, 0.0))
    scores = jnp.einsum("qt,nt->qn", weights, dense,
                        preferred_element_type=jnp.float32)
    counts = jnp.einsum("qt,nt->qn", act,
                        (pres > 0).astype(jnp.float32),
                        preferred_element_type=jnp.float32)
    matched = (counts >= required[:, None].astype(jnp.float32)) & live[None, :]
    key = jnp.where(matched, scores, -jnp.inf)
    vals, idx = lax.top_k(key, k)
    return vals, idx, matched.sum(axis=1), jnp.max(key, axis=1)


class BatchGroup:
    """Queries sharing (field, k) — batched into one [Q, T] program per
    segment."""

    def __init__(self, field: str, k: int):
        self.field = field
        self.k = k
        self.positions: list[int] = []    # index into the msearch bodies
        self.terms: list[tuple] = []
        self.idfs: list[np.ndarray] = []
        self.weights: list[np.ndarray] = []
        self.required: list[int] = []

    def add(self, pos: int, bind: dict):
        self.positions.append(pos)
        self.terms.append(tuple(bind["terms"]))
        self.idfs.append(np.asarray(bind["idfs"], _F32))
        self.weights.append(np.asarray(bind["weights"], _F32))
        self.required.append(int(bind["required"]))

    def run(self, searcher) -> dict:
        """Execute against every segment; returns {pos: (rows, total,
        max_score)} in the sequential path's row format.

        The union-of-terms kernel (``batch_bm25_union_topk``) gathers
        each DISTINCT term of the batch once per segment and scores all
        queries with one matmul, so total gather work is the union of
        the batch's postings — independent of Q — and the whole batch is
        ONE XLA program per (t_pad, q_pad, budget, k).  Round-4's
        per-query vmap paid either a compile per budget bucket or the
        heaviest budget x Q in wasted gathers (the throughput
        inversion)."""
        Q = len(self.positions)
        k = self.k
        avgdl = searcher.ctx.field_stats(self.field).avgdl
        # device handles per segment LAUNCH; host-synced once at the end
        # (4 D2H transfers per segment, not 4 per query per segment — the
        # tunnel's RTT makes tiny per-query transfers the next bottleneck)
        from opensearch_tpu.common.tasks import check_current

        launches = []             # (seg_order, vals[Q,k], idx, tot, mx)
        q_pad = pad_pow2(Q, minimum=8)
        for seg_order, seg in enumerate(searcher.segments):
            check_current()    # cancellation point per segment program
            dseg = seg.device()
            pf = seg.postings.get(self.field)
            p = dseg.postings.get(self.field)
            if pf is None or p is None:
                continue
            # distinct terms of the whole batch -> union slots
            slot_of: dict[int, int] = {}
            budget = 0
            for terms in self.terms:
                for t in terms:
                    tid = pf.term_id(t)
                    if tid >= 0 and tid not in slot_of:
                        slot_of[tid] = len(slot_of)
                        budget += int(pf.df[tid])
            t_pad = pad_pow2(len(slot_of), minimum=8)
            union_tids = np.zeros(t_pad, _I32)
            union_active = np.zeros(t_pad, bool)
            union_idfs = np.zeros(t_pad, _F32)
            weights = np.zeros((q_pad, t_pad), _F32)
            act = np.zeros((q_pad, t_pad), _F32)
            for tid, si in slot_of.items():
                union_tids[si] = tid
                union_active[si] = True
            for qi, terms in enumerate(self.terms):
                for ti, t in enumerate(terms):
                    tid = pf.term_id(t)
                    if tid < 0:
                        continue
                    si = slot_of[tid]
                    union_idfs[si] = self.idfs[qi][ti]   # idf is per term
                    weights[qi, si] += self.weights[qi][ti]
                    act[qi, si] += 1.0   # occurrence count: duplicate
                    # terms keep satisfying AND (required counts slots)
            live = searcher.ctx.live_jnp(seg, dseg)
            kk = min(k, dseg.n_pad)
            req = np.full(q_pad, np.inf, _F32)  # padding rows match nothing
            req[:Q] = self.required
            vals, idx, tot, mx = batch_bm25_union_topk(
                p["offsets"], p["doc_ids"], p["tfs"], p["doc_lens"],
                live, jnp.asarray(union_tids), jnp.asarray(union_active),
                jnp.asarray(union_idfs), jnp.asarray(weights),
                jnp.asarray(act), jnp.asarray(req),
                jnp.asarray(np.float32(avgdl)),
                n_pad=dseg.n_pad, budget=pad_bucket(budget), k=kk)
            launches.append((seg_order, vals, idx, tot, mx))
        # ONE host sync region: convert whole launches after the dispatch loop
        synced = [(so, np.asarray(v), np.asarray(i), np.asarray(t),
                   np.asarray(m)) for so, v, i, t, m in launches]
        out = {}
        for qi, pos in enumerate(self.positions):
            rows_v, rows_s, rows_l = [], [], []
            total = 0
            max_score = -np.inf
            for seg_order, avals, aidx, atot, amx in synced:
                vals, idx = avals[qi], aidx[qi]
                keep = vals > -np.inf
                rows_v.append(vals[keep])
                rows_s.append(np.full(int(keep.sum()), seg_order, _I32))
                rows_l.append(idx[keep])
                total += int(atot[qi])
                max_score = max(max_score, float(amx[qi]))
            if not rows_v:
                out[pos] = ([], 0, None)
                continue
            v = np.concatenate(rows_v)
            s = np.concatenate(rows_s)
            l = np.concatenate(rows_l)
            order = np.lexsort((l, s, -v))[: self.k]
            rows = [{"seg": int(s[i]), "local": int(l[i]),
                     "score": float(v[i])} for i in order]
            out[pos] = (rows, total,
                        None if max_score == -np.inf else float(max_score))
        return out


def plan_batches(searcher, bodies: list) -> tuple[dict, list]:
    """Partition msearch bodies into batchable groups and a fallback list.

    Returns ({(field, k): BatchGroup}, [positions needing the sequential
    path]).  Batchable = scored term-bag (TermBagPlan) with no sort /
    aggs / min_score / source filtering beyond defaults.
    """
    from opensearch_tpu.search import plan as P
    from opensearch_tpu.search.compiler import compile_query
    from opensearch_tpu.search.query_dsl import parse_query

    groups: dict = {}
    fallback = []
    for pos, body in enumerate(bodies):
        body = body or {}
        if (body.get("sort") is not None or body.get("aggs")
                or body.get("aggregations") or body.get("min_score")
                or body.get("highlight") or body.get("explain")
                or body.get("docvalue_fields") or body.get("fields")
                or body.get("timeout") is not None
                or int(body.get("from", 0)) != 0):
            # a timeout budget needs the sequential path's per-segment
            # deadline checks — one fused batch program can't stop early
            fallback.append(pos)
            continue
        try:
            plan, bind = compile_query(parse_query(body.get("query")),
                                       searcher.ctx, scored=True)
        except Exception:
            fallback.append(pos)
            continue
        if not isinstance(plan, P.TermBagPlan) or not plan.scored:
            fallback.append(pos)
            continue
        k = int(body.get("size", 10))
        if k <= 0:
            fallback.append(pos)
            continue
        key = (plan.field, k)
        g = groups.get(key)
        if g is None:
            g = groups[key] = BatchGroup(plan.field, k)
        g.add(pos, bind)
    return groups, fallback
