"""Batched multi-query execution: one device program scores MANY queries.

The reference gets throughput from many concurrent search threads each
running the doc-at-a-time hot loop (ContextIndexSearcher.java:318 under
the ``search`` threadpool).  The TPU equivalent is batching: a block of
term-bag queries is one gather->score->scatter->top_k program — a single
dispatch amortizes host<->device latency (decisive when the chip sits
behind a tunnel) and keeps the MXU/VPU busy with wide, regular work
instead of Q tiny kernels.

Served via ``ShardSearcher.msearch`` (the ``_msearch`` REST analog, ref
action/search/TransportMultiSearchAction.java): bodies that compile to a
plain scored term-bag (match / term / multi-term OR-AND) take the batched
kernel; anything else falls back to the sequential path per body —
semantics are identical either way (same impacts, same tie-breaks).

Round-6 kernel shape (impact-ordered scoring): the round-5 kernel
scattered per-posting BM25 into a dense ``[n_pad, T]`` doc x term matrix
and ran TWO ``[Q,T] @ [T,n_pad]`` einsums (scores + AND counts) — the
memory-bound core of the whole path (the 2-D scatter alone was ~60% of
batch wall time on CPU).  Now:

  1. gather the PRECOMPUTED impacts of the batch's distinct terms once
     (``DeviceSegment.impacts`` — no per-posting norm math, no doc_lens
     gather);
  2. ONE flat 1-D scatter-add of ``idf * impact`` into a
     ``[T * n_pad]`` arena (a 1-D scatter is ~6x cheaper than the same
     updates through a 2-D index);
  3. per-query-term weighted ROW gathers accumulate straight into the
     ``[Q, n_pad]`` score block — each query touches only its OWN few
     term rows (contiguous, cache-friendly) instead of a [Q,T]x[T,n]
     matmul over the whole union;
  4. the matched-count side is built the same way, and is SKIPPED
     entirely (static flag) when every query in the group is a plain OR
     bag — scores > 0 is then exactly the match mask;
  5. batched ``lax.top_k`` over [Q, n_pad].

Accumulation order per (query, doc) equals the sequential kernel's
(term order within the query), so batched and sequential scores are
byte-identical — the property tests/test_impacts.py pins.

Group inputs (union slots, per-query term rows) are cached on the
searcher keyed by the group's value signature, so a REPEATED msearch
batch does zero host-side assembly.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import opensearch_tpu.common.jaxenv  # noqa: F401
import jax
import jax.numpy as jnp
from jax import lax

from opensearch_tpu.common.device_ledger import \
    device_ledger as _device_ledger
from opensearch_tpu.common.telemetry import metrics as _metrics
from opensearch_tpu.index.segment import pad_bucket, pad_pow2
from opensearch_tpu.ops import bm25 as bm25_ops

_I32 = np.int32
_F32 = np.float32


@partial(jax.jit, static_argnames=("n_pad", "budget", "k", "need_counts"))
def batch_impact_union_topk(offsets, doc_ids, impacts, live,
                            union_tids, union_active, union_idfs,
                            qslots, qweights, qact, required,
                            *, n_pad: int, budget: int, k: int,
                            need_counts: bool):
    """Score Q term-bag queries against one segment in ONE program via
    the union-of-terms + precomputed-impacts formulation (see module
    docstring).  ``union_tids``/``union_active``/``union_idfs`` are [T];
    ``qslots``/``qweights``/``qact`` are [Q, TQ] — query q's j-th term
    as a union slot, its boost weight, and its occurrence count (0 on
    padding, so duplicate terms keep satisfying AND); ``required`` is
    [Q] (inf on padding rows).  Returns (vals [Q, k], idx [Q, k],
    totals [Q], maxes [Q])."""
    d, imp, slot, valid = bm25_ops.gather_postings(
        offsets, doc_ids, impacts, union_tids, union_active,
        budget=budget, pad_doc=n_pad - 1)
    base = jnp.where(valid, union_idfs[slot] * imp, 0.0)
    t_pad = union_tids.shape[0]
    flat_idx = slot.astype(jnp.int64) * n_pad + d
    dense = jnp.zeros(t_pad * n_pad, jnp.float32).at[flat_idx].add(
        base).reshape(t_pad, n_pad)
    q_pad, tq = qslots.shape
    scores = jnp.zeros((q_pad, n_pad), jnp.float32)
    for j in range(tq):
        scores = scores + qweights[:, j: j + 1] * dense[qslots[:, j], :]
    if need_counts:
        pres = jnp.zeros(t_pad * n_pad, jnp.float32).at[flat_idx].add(
            valid.astype(jnp.float32)).reshape(t_pad, n_pad)
        counts = jnp.zeros((q_pad, n_pad), jnp.float32)
        for j in range(tq):
            counts = counts + qact[:, j: j + 1] * jnp.minimum(
                pres[qslots[:, j], :], 1.0)
        matched = (counts >= required[:, None]) & live[None, :]
    else:
        # every query is a positive-weight OR bag: score > 0 iff matched
        matched = (scores > 0.0) & live[None, :]
    key = jnp.where(matched, scores, -jnp.inf)
    vals, idx = lax.top_k(key, k)
    return vals, idx, matched.sum(axis=1), jnp.max(key, axis=1)


class BatchGroup:
    """Queries sharing (field, k) — batched into one program per
    segment."""

    def __init__(self, field: str, k: int):
        self.field = field
        self.k = k
        self.positions: list[int] = []    # index into the msearch bodies
        self.terms: list[tuple] = []
        self.idfs: list[np.ndarray] = []
        self.weights: list[np.ndarray] = []
        self.required: list[int] = []
        # group-level scanned/pruned counts of the last run() — shared
        # by every member's insight record (one pass served the group)
        self.last_stats = {"pruned": 0, "scanned": 0}

    def add(self, pos: int, bind: dict):
        self.positions.append(pos)
        self.terms.append(tuple(bind["terms"]))
        self.idfs.append(np.asarray(bind["idfs"], _F32))
        self.weights.append(np.asarray(bind["weights"], _F32))
        self.required.append(int(bind["required"]))
        self.avgdl = float(bind["avgdl"])

    def signature(self) -> tuple:
        """Value identity of the batch: same signature -> identical
        staged inputs (idfs/avgdl derive from the searcher's stats, and
        the prep cache lives ON that searcher)."""
        return (self.field, self.k, tuple(self.terms),
                tuple(tuple(float(x) for x in w) for w in self.weights),
                tuple(self.required))

    def _prepare(self, searcher) -> dict:
        """Host-side assembly of the per-segment union/query inputs —
        everything that does NOT depend on the live bitmap, staged once
        and reused for every identical batch against this searcher.
        All stagings are ledger-recorded under one ``batch_group``
        owner whose lifetime follows this prep's cache entry."""
        from opensearch_tpu.common.device_ledger import (GroupCloser,
                                                         device_ledger)

        led = device_ledger()
        group = led.open_group(index=searcher.index_name,
                               shard=searcher.shard_id,
                               segment=f"msearch[{self.field},{self.k}]")
        Q = len(self.positions)
        q_pad = pad_pow2(Q, minimum=8)
        tq = pad_pow2(max((len(t) for t in self.terms), default=1),
                      minimum=1)
        need_counts = any(r != 1 for r in self.required) \
            or any((w <= 0).any() for w in self.weights) \
            or any((i <= 0).any() for i in self.idfs)
        req = np.full(q_pad, np.inf, _F32)   # padding rows match nothing
        req[:Q] = self.required
        req_j = led.stage(group, req, kind="batch_group",
                          field=self.field, name="required")
        segs = []
        pruned = 0
        for seg_order, seg in enumerate(searcher.segments):
            pf = seg.postings.get(self.field)
            if pf is None or seg.device().postings.get(self.field) is None:
                continue
            # distinct terms of the whole batch -> union slots
            slot_of: dict[int, int] = {}
            budget = 0
            for terms in self.terms:
                for t in terms:
                    tid = pf.term_id(t)
                    if tid >= 0 and tid not in slot_of:
                        slot_of[tid] = len(slot_of)
                        budget += int(pf.df[tid])
            if not slot_of:
                # no query term exists in this segment: nothing can
                # match, skip without staging or dispatch
                pruned += 1
                continue
            t_pad = pad_pow2(len(slot_of), minimum=8)
            union_tids = np.zeros(t_pad, _I32)
            union_active = np.zeros(t_pad, bool)
            union_idfs = np.zeros(t_pad, _F32)
            qslots = np.zeros((q_pad, tq), _I32)
            qweights = np.zeros((q_pad, tq), _F32)
            qact = np.zeros((q_pad, tq), _F32)
            for tid, si in slot_of.items():
                union_tids[si] = tid
                union_active[si] = True
            for qi, terms in enumerate(self.terms):
                j = 0
                for ti, t in enumerate(terms):
                    tid = pf.term_id(t)
                    if tid < 0:
                        continue
                    si = slot_of[tid]
                    union_idfs[si] = self.idfs[qi][ti]  # idf is per term
                    qslots[qi, j] = si
                    qweights[qi, j] = self.weights[qi][ti]
                    qact[qi, j] = 1.0   # occurrences: duplicate terms
                    j += 1              # keep satisfying AND
            sid = seg.seg_id
            segs.append((seg_order, {
                "union_tids": led.stage(group, union_tids,
                                        kind="batch_group",
                                        field=self.field,
                                        name=f"{sid}/union_tids"),
                "union_active": led.stage(group, union_active,
                                          kind="batch_group",
                                          field=self.field,
                                          name=f"{sid}/union_active"),
                "union_idfs": led.stage(group, union_idfs,
                                        kind="batch_group",
                                        field=self.field,
                                        name=f"{sid}/union_idfs"),
                "qslots": led.stage(group, qslots, kind="batch_group",
                                    field=self.field,
                                    name=f"{sid}/qslots"),
                "qweights": led.stage(group, qweights,
                                      kind="batch_group",
                                      field=self.field,
                                      name=f"{sid}/qweights"),
                "qact": led.stage(group, qact, kind="batch_group",
                                  field=self.field, name=f"{sid}/qact"),
                "budget": pad_bucket(budget),
            }))
        if pruned:
            _metrics().counter("search.segments_pruned").inc(pruned)
        led.seal(group)
        return {"need_counts": need_counts, "required": req_j,
                "segs": segs, "q_pad": q_pad,
                "_ledger": GroupCloser(led, group)}

    def _bind(self, qi: int) -> dict:
        return {"terms": self.terms[qi], "idfs": self.idfs[qi],
                "weights": self.weights[qi],
                "required": self.required[qi], "avgdl": self.avgdl}

    def _run_host(self, searcher, prof=None) -> dict:
        """CPU-backend batch execution: every query scores host-side
        via ``TermBagPlan.host_topk`` over the shared per-segment impact
        tables — byte-identical to the sequential path by construction
        (same function, same accumulation order).  See ops/bm25.py
        ``host_scoring_enabled`` for why XLA:CPU scatter loses to the
        host here."""
        import time

        from opensearch_tpu.common.tasks import check_current
        from opensearch_tpu.search.plan import TermBagPlan

        if prof is not None:
            prof.set("execution_path", "host_batched")
        plan = TermBagPlan(field=self.field, scored=True)
        acc = {pos: {"v": [], "s": [], "l": [], "tot": 0, "mx": -np.inf}
               for pos in self.positions}
        pruned = 0
        scanned = 0
        if prof is not None:
            # profiled groups keep the serial segment-outer loop so the
            # per-segment dispatch attribution includes scoring time
            for seg_order, seg in enumerate(searcher.segments):
                check_current()    # cancellation point per segment
                t_seg = time.monotonic()
                pf = seg.postings.get(self.field)
                if pf is None:
                    continue
                if not any(pf.term_id(t) >= 0
                           for terms in self.terms for t in terms):
                    pruned += 1
                    prof.seg_pruned(seg.seg_id, "pruned_can_match",
                                    time.monotonic() - t_seg)
                    continue
                live = searcher.ctx.lives[id(seg)]
                for qi, pos in enumerate(self.positions):
                    vals, idx, tot, mx = plan.host_topk(  # engine-ok: batch host backend
                        self._bind(qi), seg, live,
                        min(self.k, seg.n_docs), None)
                    a = acc[pos]
                    a["v"].append(vals)
                    a["s"].append(np.full(len(vals), seg_order, _I32))
                    a["l"].append(idx)
                    a["tot"] += int(tot)
                    a["mx"] = max(a["mx"], float(mx))
                scanned += 1
                prof.seg_scanned(seg.seg_id, time.monotonic() - t_seg)
        else:
            surviving = []         # (seg_order, seg, live)
            for seg_order, seg in enumerate(searcher.segments):
                check_current()    # cancellation point per segment
                pf = seg.postings.get(self.field)
                if pf is None:
                    continue
                if not any(pf.term_id(t) >= 0
                           for terms in self.terms for t in terms):
                    pruned += 1    # no query term here: skip scoring
                    continue
                surviving.append((seg_order, seg,
                                  searcher.ctx.lives[id(seg)]))
                scanned += 1

            def score_member(qi):
                bindq = self._bind(qi)
                a = acc[self.positions[qi]]
                for seg_order, seg, live in surviving:
                    vals, idx, tot, mx = plan.host_topk(  # engine-ok: batch host backend
                        bindq, seg, live, min(self.k, seg.n_docs), None)
                    a["v"].append(vals)
                    a["s"].append(np.full(len(vals), seg_order, _I32))
                    a["l"].append(idx)
                    a["tot"] += int(tot)
                    a["mx"] = max(a["mx"], float(mx))

            if len(self.positions) > 1 and surviving:
                # members are independent: fan the per-member scoring
                # loop across the engine threadpool (the batched-group
                # analog of the executor's multi-segment host fan-out)
                from opensearch_tpu.search.engine import query_engine
                query_engine().pool.run_all(
                    [(lambda qi=qi: score_member(qi))
                     for qi in range(len(self.positions))])
            else:
                for qi in range(len(self.positions)):
                    score_member(qi)
        if pruned:
            _metrics().counter("search.segments_pruned").inc(pruned)
        # group-level attribution the msearch member insight records
        # carry (shared by construction — ONE pass served the group)
        self.last_stats = {"pruned": pruned, "scanned": scanned}
        t_red = time.monotonic() if prof is not None else 0.0
        out = {}
        for pos in self.positions:
            a = acc[pos]
            if not a["v"]:
                out[pos] = ([], 0, None)
                continue
            v = np.concatenate(a["v"])
            s = np.concatenate(a["s"])
            l = np.concatenate(a["l"])
            order = np.lexsort((l, s, -v))[: self.k]
            rows = [{"seg": int(s[i]), "local": int(l[i]),
                     "score": float(v[i])} for i in order]
            out[pos] = (rows, a["tot"],
                        None if a["mx"] == -np.inf else float(a["mx"]))
        if prof is not None:
            prof.add("reduce", time.monotonic() - t_red)
        return out

    def run(self, searcher, prof=None) -> dict:
        """Execute against every segment; returns {pos: (rows, total,
        max_score)} in the sequential path's row format.

        On the CPU backend the whole batch scores host-side
        (``_run_host``).  Otherwise: device handles per segment LAUNCH;
        host-synced once at the end (4 D2H transfers per segment, not 4
        per query per segment — the tunnel's RTT makes tiny per-query
        transfers the next bottleneck).  ``prof`` is the shared GROUP
        profiler (see ShardSearcher.msearch)."""
        from opensearch_tpu.common.device_health import (device_health,
                                                         is_device_error)

        health = device_health()
        if bm25_ops.host_scoring_enabled():
            return self._run_host(searcher, prof=prof)
        if not (health.allow("batch") and health.allow("staging")):
            # open device breaker: the whole group scores on the host
            # impact tables — byte-identical (the PR-5 invariant)
            _device_ledger().record_host_fallback()
            return self._run_host(searcher, prof=prof)
        try:
            return self._run_device(searcher, health, prof=prof)
        except Exception as exc:
            if not is_device_error(exc):
                raise
            # counted: record_failure -> device.errors; the byte-
            # identical host path serves the group instead of failing
            # the whole msearch/continuous batch
            health.record_failure("batch", exc)
            _device_ledger().record_host_fallback()
            return self._run_host(searcher, prof=prof)

    def _run_device(self, searcher, health, prof=None) -> dict:
        import time

        from opensearch_tpu.common.cache import attached_cache
        from opensearch_tpu.common.device_health import check_finite
        from opensearch_tpu.common.tasks import check_current

        if prof is not None:
            prof.set("execution_path", "device_batched")
            t_prep = time.monotonic()
        cache = attached_cache(searcher, "_batch_prep_cache",
                               name="search.batch_prep",
                               max_weight=64 << 20,
                               breaker="fielddata")
        sig = self.signature()
        prep = cache.get(sig)
        if prep is None:
            if prof is not None:
                prof.set("batch_prep_cache", "miss")
            prep = self._prepare(searcher)
            cache.put(sig, prep)
        elif prof is not None:
            prof.set("batch_prep_cache", "hit")
        if prof is not None:
            prof.add("prepare", time.monotonic() - t_prep)
            # segments the union prep dropped never dispatch: no query
            # term exists there (the batch path's can-match analog)
            staged = {so for so, _sp in prep["segs"]}
            for so, seg in enumerate(searcher.segments):
                if so not in staged:
                    prof.seg_pruned(seg.seg_id, "pruned_can_match", 0.0)
        self.last_stats = {
            "pruned": len(searcher.segments) - len(prep["segs"]),
            "scanned": len(prep["segs"])}
        launches = []             # (seg_order, vals[Q,k], idx, tot, mx)
        for seg_order, sp in prep["segs"]:
            check_current()    # cancellation point per segment program
            t_seg = time.monotonic() if prof is not None else 0.0
            seg = searcher.segments[seg_order]
            dseg = seg.device()
            # the batched union kernel stays on the f32 lowering: on
            # quantized segments the full posting columns demand-stage
            # here (DeviceSegment.ensure_postings)
            dseg.ensure_postings(self.field)
            impacts = dseg.impacts(self.field, self.avgdl)  # quantize-ok: batch union stays on the f32 lowering
            live = searcher.ctx.live_jnp(seg, dseg)
            kk = min(self.k, dseg.n_pad)
            vals, idx, tot, mx = batch_impact_union_topk(  # engine-ok: batch device backend
                dseg.postings[self.field]["offsets"],
                dseg.postings[self.field]["doc_ids"],
                impacts, live, sp["union_tids"], sp["union_active"],
                sp["union_idfs"], sp["qslots"], sp["qweights"],
                sp["qact"], prep["required"],
                n_pad=dseg.n_pad, budget=sp["budget"], k=kk,
                need_counts=prep["need_counts"])
            launches.append((seg_order, vals, idx, tot, mx))
            _device_ledger().record_dispatch(
                getattr(dseg, "_ledger_group", None))
            if prof is not None:
                prof.seg_scanned(seg.seg_id, time.monotonic() - t_seg)
        # ONE host sync region: convert whole launches after the dispatch loop
        t_sync = time.monotonic()
        t_red = t_sync if prof is not None else 0.0
        synced = [(so, np.asarray(v), np.asarray(i), np.asarray(t),
                   np.asarray(m)) for so, v, i, t, m in launches]
        if synced:
            _device_ledger().record_fetch(
                sum(v.nbytes + i.nbytes + t.nbytes + m.nbytes
                    for _so, v, i, t, m in synced),
                time.monotonic() - t_sync)
        # result-sanity guard at the batch sync region: non-finite
        # scores mean the device returned poison — discard the whole
        # group's device results and recompute on the byte-identical
        # host path (files a flight-recorder capture + feeds the
        # batch breaker via record_poison)
        from opensearch_tpu.common.device_health import check_finite
        for so, v, _i, _t, _m in synced:
            bad = check_finite(v)
            if bad:
                seg = searcher.segments[so]
                health.record_poison(
                    kernel="batch_impact_union_topk",
                    segment=seg.seg_id, index=searcher.index_name,
                    shard=searcher.shard_id, bad=bad)
                _device_ledger().record_host_fallback()
                return self._run_host(searcher, prof=prof)
        health.record_success("batch")
        out = {}
        for qi, pos in enumerate(self.positions):
            rows_v, rows_s, rows_l = [], [], []
            total = 0
            max_score = -np.inf
            for seg_order, avals, aidx, atot, amx in synced:
                vals, idx = avals[qi], aidx[qi]
                keep = vals > -np.inf
                rows_v.append(vals[keep])
                rows_s.append(np.full(int(keep.sum()), seg_order, _I32))
                rows_l.append(idx[keep])
                total += int(atot[qi])
                max_score = max(max_score, float(amx[qi]))
            if not rows_v:
                out[pos] = ([], 0, None)
                continue
            v = np.concatenate(rows_v)
            s = np.concatenate(rows_s)
            l = np.concatenate(rows_l)
            order = np.lexsort((l, s, -v))[: self.k]
            rows = [{"seg": int(s[i]), "local": int(l[i]),
                     "score": float(v[i])} for i in order]
            out[pos] = (rows, total,
                        None if max_score == -np.inf else float(max_score))
        if prof is not None:
            prof.add("reduce", time.monotonic() - t_red)
        return out


def plan_batches(searcher, bodies: list) -> tuple[dict, list]:
    """Partition msearch bodies into batchable groups and a fallback list.

    Returns ({(field, k): BatchGroup}, [positions needing the sequential
    path]).  Batchable = scored term-bag (TermBagPlan) with no sort /
    aggs / min_score / source filtering beyond defaults.  Compilation
    goes through the searcher's plan cache, so repeated bodies do zero
    parse/compile work here.
    """
    from opensearch_tpu.search import plan as P

    groups: dict = {}
    fallback = []
    for pos, body in enumerate(bodies):
        body = body or {}
        if (body.get("sort") is not None or body.get("aggs")
                or body.get("aggregations") or body.get("min_score")
                or body.get("highlight") or body.get("explain")
                or body.get("docvalue_fields") or body.get("fields")
                or body.get("collapse") or body.get("rescore")
                or body.get("suggest") or body.get("search_after")
                or body.get("stored_fields") or body.get("script_fields")
                or body.get("post_filter")
                or body.get("track_total_hits") is False
                or body.get("timeout") is not None
                or int(body.get("from", 0)) != 0):
            # a timeout budget needs the sequential path's per-segment
            # deadline checks — one fused batch program can't stop
            # early; collapse/rescore/suggest shape the response beyond
            # plain top-k; track_total_hits:false may legally return
            # lower-bound totals sequentially (k-th pruning) which the
            # exact batched totals would not reproduce
            fallback.append(pos)
            continue
        try:
            plan, bind = searcher.compiled(body.get("query"), scored=True)
        except Exception:
            fallback.append(pos)
            continue
        if not isinstance(plan, P.TermBagPlan) or not plan.scored:
            fallback.append(pos)
            continue
        k = int(body.get("size", 10))
        if k <= 0:
            fallback.append(pos)
            continue
        key = (plan.field, k)
        g = groups.get(key)
        if g is None:
            g = groups[key] = BatchGroup(plan.field, k)
        g.add(pos, bind)
    return groups, fallback
